"""Benchmark suite mirroring the reference's benchmark infrastructure
(reference benchmark/benchmarks.jl:1-29 `SUITE["evaluation"]` and
benchmark/single_eval.jl:1-28), plus the framework's own batched-population
shapes. Prints one JSON line per entry.

Usage:
    python benchmark/suite.py            # run on the default backend
    JAX_PLATFORMS=cpu python benchmark/suite.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


_EMIT_PLATFORM = [None]  # set by the runner before cases execute


def _emit(rec, out):
    """Print a result row the moment it exists (stamped with the
    platform) AND collect it: a later fault in the same process — a
    wedged TPU client can take the whole interpreter down — must not
    erase rows already measured (r04 lost the precision_ratio row to
    exactly that)."""
    if _EMIT_PLATFORM[0] is not None:
        rec = {**rec, "platform": _EMIT_PLATFORM[0]}
    print(json.dumps(rec), flush=True)
    out.append(rec)
    return rec


def _median_time(fn, reps=5):
    fn()  # warmup/compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def bench_eval_fixed_tree():
    """The reference's SUITE["evaluation"]: a fixed 15-node tree over
    X = 5x1000, Float32/Float64 (BigFloat has no TPU analog; bfloat16 is
    the TPU-native third precision)."""
    import jax
    import jax.numpy as jnp

    import symbolicregression_jl_tpu as sr

    ops = sr.make_operator_set(["+", "-", "/", "*"], ["cos", "exp"])
    # same topology as benchmark/benchmarks.jl:7-19:
    # (cos(1.0+x1)*exp(-1.0) stacked into +/- and * / branches over x2/x3)
    s = ("((cos(1 + x1) * exp(-1)) - (x2 / x3)) + "
         "((cos(1 + x1) * exp(-1)) * (x2 / x3))")
    expr = sr.parse_expression(s, ops)
    rng = np.random.default_rng(0)
    X_h = rng.standard_normal((5, 1000))

    out = []
    for dtype_name, dtype in [
        ("float32", jnp.float32),
        ("bfloat16", jnp.bfloat16),
    ]:
        tree = jax.tree_util.tree_map(
            jnp.asarray, sr.encode_tree(expr, 24)
        )
        tree = tree._replace(cval=tree.cval.astype(dtype))
        X = jnp.asarray(X_h, dtype)
        f = jax.jit(lambda t, X: sr.eval_tree(t, X, ops))
        y, ok = f(tree, X)
        dt = _median_time(lambda: jax.block_until_ready(f(tree, X)))
        out.append(
            {
                "suite": "evaluation",
                "case": dtype_name,
                "tree_nodes": int(tree.length),
                "rows": 1000,
                "median_s": dt,
            }
        )
    return out


def bench_single_eval_48_nodes():
    """The reference's single_eval.jl micro: 48-node tree on 3x200."""
    import jax
    import jax.numpy as jnp

    import symbolicregression_jl_tpu as sr

    ops = sr.make_operator_set(["+", "*", "/", "-"], ["cos", "sin"])
    s = (
        "((x1 + x1) * ((-0.5982493 / x0) / -0.54734415)) + "
        "(sin(cos(sin(1.2926733 - 1.6606787) / "
        "sin(((0.14577048 * x0) + ((0.111149654 + x0) - -0.8298334)) "
        "- -1.2071426)) * (cos(x2 - 2.3201916) + ((x0 - (x0 * x1)) / x1)))"
        " / (0.14854191 - ((cos(x1) * -1.6047639) - 0.023943262)))"
    )
    expr = sr.parse_expression(s, ops)
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((3, 200)), jnp.float32)
    tree = jax.tree_util.tree_map(jnp.asarray, sr.encode_tree(expr, 56))
    f = jax.jit(lambda t, X: sr.eval_tree(t, X, ops))
    f(tree, X)
    dt = _median_time(lambda: jax.block_until_ready(f(tree, X)))
    return [
        {
            "suite": "single_eval",
            "case": "48_nodes_3x200",
            "tree_nodes": int(tree.length),
            "median_s": dt,
        }
    ]


def bench_population_scoring():
    """This framework's own shape: whole-population fused scoring (the
    per-cycle hot call of the evolution engine)."""
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.models.fitness import score_trees
    from symbolicregression_jl_tpu.models.mutate_device import (
        gen_random_tree_fixed_size,
    )
    from symbolicregression_jl_tpu.models.options import make_options

    options = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        maxsize=20,
    )
    n_trees, n_rows = 4096, 1000
    sizes = jax.random.randint(jax.random.PRNGKey(1), (n_trees,), 3, 20)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(
            k, s, 5, options.operators, options.max_len
        )
    )(jax.random.split(jax.random.PRNGKey(0), n_trees), sizes)
    X = jax.random.normal(jax.random.PRNGKey(2), (5, n_rows), jnp.float32)
    y = 2.0 * jnp.cos(X[4]) + X[1] ** 2 - 2.0

    f = jax.jit(
        lambda t: score_trees(t, X, y, None, jnp.float32(1.0), options)
    )
    f(trees)
    dt = _median_time(lambda: jax.block_until_ready(f(trees)))
    return [
        {
            "suite": "population_scoring",
            "case": f"{n_trees}x{n_rows}",
            "median_s": dt,
            "trees_rows_per_s": n_trees * n_rows / dt,
        }
    ]


def bench_bucketed_eval():
    """Length-bucketed vs flat jnp interpreter evaluation (ISSUE 5): an
    8192-tree population with a skewed length distribution (80% short /
    15% mid / 5% long — the shape GP populations actually have) scored
    flat and through the eval_bucket_ladder dispatch. Reports both
    trees-rows/s rates, their ratio (the acceptance target is >=1.5x on
    CPU), and the bit-identity of the two loss vectors. eval_backend is
    pinned to 'jnp' so the case measures the interpreter on every
    platform (the Pallas kernel path has its own bucket dispatch —
    bench_pallas_bucketed covers it)."""
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.models.fitness import eval_loss_trees
    from symbolicregression_jl_tpu.models.mutate_device import (
        gen_random_tree_fixed_size,
    )
    from symbolicregression_jl_tpu.models.options import make_options

    options = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        maxsize=20,
    )
    ops = options.operators
    loss_fn = options.elementwise_loss
    n_trees, n_rows = 8192, 1000
    rng = np.random.default_rng(0)
    u = rng.random(n_trees)
    sizes = np.where(
        u < 0.80, rng.integers(3, 7, n_trees),
        np.where(u < 0.95, rng.integers(7, 13, n_trees),
                 rng.integers(13, 21, n_trees)),
    ).astype(np.int32)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(
            k, s, 5, ops, options.max_len
        )
    )(jax.random.split(jax.random.PRNGKey(0), n_trees), jnp.asarray(sizes))
    X = jax.random.normal(jax.random.PRNGKey(2), (5, n_rows), jnp.float32)
    y = 2.0 * jnp.cos(X[4]) + X[1] ** 2 - 2.0
    ladder = (0.25, 0.5, 0.75, 1.0)

    flat_fn = jax.jit(
        lambda t: eval_loss_trees(t, X, y, None, ops, loss_fn,
                                  backend="jnp")
    )
    buck_fn = jax.jit(
        lambda t: eval_loss_trees(t, X, y, None, ops, loss_fn,
                                  backend="jnp", bucket_ladder=ladder)
    )
    l_flat = np.asarray(flat_fn(trees))
    l_buck = np.asarray(buck_fn(trees))
    identical = bool(np.array_equal(l_flat, l_buck))
    dt_flat = _median_time(lambda: jax.block_until_ready(flat_fn(trees)))
    dt_buck = _median_time(lambda: jax.block_until_ready(buck_fn(trees)))
    work = n_trees * n_rows
    return [
        {
            "suite": "bucketed_eval",
            "case": "flat",
            "median_s": dt_flat,
            "trees_rows_per_s": work / dt_flat,
        },
        {
            "suite": "bucketed_eval",
            "case": f"ladder{'-'.join(str(f) for f in ladder)}",
            "median_s": dt_buck,
            "trees_rows_per_s": work / dt_buck,
        },
        {
            "suite": "bucketed_eval",
            "case": "summary",
            "bit_identical": identical,
            "bucketed_vs_flat": dt_flat / dt_buck,
            "mean_tree_len": float(np.asarray(trees.length).mean()),
            "max_len_slots": options.max_len,
        },
    ]


def _suite_telemetry_dir(prefix):
    """Per-case telemetry directory. When the watcher exported
    SRTPU_BENCH_TELEMETRY_DIR (tpu_watcher.py --telemetry-dir) the logs
    land THERE, so its event-log classifier sees this case's
    run_start/dispatch_fault/saved_state/run_end trail instead of
    falling back to stdout scraping; otherwise a private tmpdir."""
    import tempfile

    d = os.environ.get("SRTPU_BENCH_TELEMETRY_DIR")
    if d:
        os.makedirs(d, exist_ok=True)
        return d
    return tempfile.mkdtemp(prefix=prefix)


def bench_telemetry():
    """Unified search telemetry (ISSUE 7): a short search with
    Options.telemetry writes a JSONL event log. Asserts the log parses
    as strict JSON, validates against the checked-in schema
    (telemetry/event_schema_v1.json), and contains all seven stage spans
    — and reports the per-stage wall time columns, the per-iteration
    observability the fused engine never had."""
    import symbolicregression_jl_tpu as sr
    from symbolicregression_jl_tpu.telemetry import (
        STAGES,
        validate_events_file,
    )

    d = _suite_telemetry_dir("srtpu_suite_telemetry_")
    rng = np.random.default_rng(0)
    X = rng.standard_normal((3, 128)).astype(np.float32)
    y = 2.0 * np.cos(X[2]) + X[0] ** 2 - 0.5
    t0 = time.perf_counter()
    r = sr.equation_search(
        X, y,
        binary_operators=["+", "-", "*"], unary_operators=["cos"],
        npopulations=4, npop=24, ncycles_per_iteration=30, maxsize=12,
        niterations=2, seed=0, verbosity=0, progress=False,
        telemetry=True, telemetry_dir=d,
    )
    wall_s = time.perf_counter() - t0
    # newest log: a shared watcher telemetry dir may hold earlier runs
    paths = sorted(
        (os.path.join(d, f) for f in os.listdir(d)
         if f.endswith(".jsonl")),
        key=os.path.getmtime,
    )
    report = validate_events_file(paths[-1])
    stage_s = {s: 0.0 for s in STAGES}
    n_metrics = 0
    with open(paths[-1]) as f:
        for line in f:
            e = json.loads(line)
            if e["type"] == "span" and e["name"] in stage_s:
                stage_s[e["name"]] += e["duration_s"]
            elif e["type"] == "metrics":
                n_metrics += 1
    row = {
        "suite": "telemetry",
        "case": "stage_times",
        "schema_ok": report["ok"],
        "events": report["events"],
        "spans_complete": all(stage_s[s] > 0.0 for s in STAGES),
        "metrics_events": n_metrics,
        "search_wall_s": wall_s,
        "hof_size": len(r.frontier()),
        "event_log": paths[-1],
    }
    # one stage-time column per stage, the per-stage attribution rows
    # downstream dashboards join on (mutate/eval are one-shot probe
    # dispatches, the in-loop phases are summed over iterations)
    row.update({f"stage_{s}_s": round(stage_s[s], 4) for s in STAGES})
    if report["problems"]:
        row["schema_problems"] = report["problems"][:3]
    return [row]


def bench_run_doctor():
    """Run doctor end to end (ISSUE 10): a tiny search with telemetry on
    must yield an event log the doctor reads as HEALTHY — all seven
    stage spans present, per-island diversity in (0, 1], the exact
    hypervolume and per-mutation acceptance populated. This is the
    closed loop: the search writes the trail, the analyzer interprets
    it, and CI asserts the interpretation."""
    import symbolicregression_jl_tpu as sr
    from symbolicregression_jl_tpu.telemetry.analyze import (
        analyze_run,
        resolve_log,
    )

    d = _suite_telemetry_dir("srtpu_suite_doctor_")
    rng = np.random.default_rng(0)
    X = rng.standard_normal((3, 128)).astype(np.float32)
    y = 2.0 * np.cos(X[2]) + X[0] ** 2 - 0.5
    t0 = time.perf_counter()
    sr.equation_search(
        X, y,
        binary_operators=["+", "-", "*"], unary_operators=["cos"],
        npopulations=4, npop=24, ncycles_per_iteration=30, maxsize=12,
        niterations=2, seed=0, verbosity=0, progress=False,
        telemetry=True, telemetry_dir=d,
    )
    wall_s = time.perf_counter() - t0
    report = analyze_run(resolve_log(d))
    div = report.get("diversity") or {}
    div_ok = bool(div) and 0.0 < div["last"] <= 1.0
    return [{
        "suite": "run_doctor",
        "case": "healthy_search",
        "ok": (
            report["verdict"] == "healthy"
            and report["spans_complete"]
            and div_ok
        ),
        "verdict": report["verdict"],
        "spans_complete": report["spans_complete"],
        "diversity_last": div.get("last"),
        "diversity_ok": div_ok,
        "hypervolume_last": (report.get("hypervolume") or {}).get("last"),
        "best_loss_last": (report.get("best_loss") or {}).get("last"),
        "mutation_accept_rate": (
            report.get("mutation_accept_rate") or {}
        ).get("last"),
        "metric_snapshots": report.get("metric_snapshots"),
        "search_wall_s": wall_s,
        "event_log": report.get("path"),
    }]


def bench_profile():
    """srprof end to end (ISSUE 12): a tiny search with telemetry on
    must leave an event log whose `profile` events let the report CLI
    render per-stage modeled element-ops/bytes, measured wall time, and
    a non-null modeled roofline fraction in (0, 1] for ALL seven stages
    — the modeled-vs-measured closed loop ROADMAP #2's exit criterion
    asks for, asserted from a real search log."""
    import symbolicregression_jl_tpu as sr
    from symbolicregression_jl_tpu.telemetry.analyze import resolve_log
    from symbolicregression_jl_tpu.telemetry.profile import (
        profile_report,
        render_text,
    )

    d = _suite_telemetry_dir("srtpu_suite_profile_")
    rng = np.random.default_rng(0)
    X = rng.standard_normal((3, 128)).astype(np.float32)
    y = 2.0 * np.cos(X[2]) + X[0] ** 2 - 0.5
    t0 = time.perf_counter()
    sr.equation_search(
        X, y,
        binary_operators=["+", "-", "*"], unary_operators=["cos"],
        npopulations=4, npop=24, ncycles_per_iteration=30, maxsize=12,
        niterations=2, seed=0, verbosity=0, progress=False,
        telemetry=True, telemetry_dir=d,
    )
    wall_s = time.perf_counter() - t0
    report = profile_report(resolve_log(d))
    text = render_text(report)
    stages = report["stages"]
    fracs = {
        s: row.get("roofline_fraction") for s, row in stages.items()
    }
    fracs_ok = len(fracs) == 7 and all(
        isinstance(f, float) and 0.0 < f <= 1.0 for f in fracs.values()
    )
    row = {
        "suite": "profile",
        "case": "modeled_vs_measured",
        "ok": report["complete"] and fracs_ok and bool(text),
        "stages": len(stages),
        "fractions_ok": fracs_ok,
        "compile_total_s": report.get("compile_total_s"),
        "search_wall_s": wall_s,
        "report_lines": text.count("\n") + 1,
        "event_log": report.get("path"),
    }
    row.update({
        f"roofline_{s}": (round(f, 4) if isinstance(f, float) else None)
        for s, f in fracs.items()
    })
    return [row]


def bench_resilience():
    """Preemption-tolerant search (ISSUE 11): a fault injected at
    dispatch 1 of a 2-iteration search (the in-process `raise` form of
    a preemption — the real-SIGKILL/cross-process form is pinned by
    tests/test_ad_resilience.py's slow tier, which this case's
    subprocess budget can't afford), snapshotting every dispatch,
    auto-resumed by the resilience supervisor — the final hall of fame
    must be BIT-IDENTICAL to the uninterrupted baseline (the snapshot
    carries the host key chain, docs/resilience.md), the resumed run's
    event log must read HEALTHY to the run doctor, and the interrupted
    attempt's log must read faulted+resumable. This is the closed loop
    of ROADMAP #3: fault -> snapshot -> classify -> resume, end to end,
    by construction instead of waiting for a real outage."""
    import symbolicregression_jl_tpu as sr
    from symbolicregression_jl_tpu.resilience import (
        FaultPlan,
        clear_fault_plan,
        set_fault_plan,
        supervised_search,
    )
    from symbolicregression_jl_tpu.telemetry.analyze import (
        analyze_run,
        resolve_log,
    )

    tele_d = _suite_telemetry_dir("srtpu_suite_resilience_")
    snap_d = os.environ.get("SRTPU_BENCH_SNAPSHOT_DIR")
    if snap_d:
        os.makedirs(snap_d, exist_ok=True)
    else:
        import tempfile

        snap_d = tempfile.mkdtemp(prefix="srtpu_suite_resilience_snap_")
    snap = os.path.join(snap_d, "resilience_case.ckpt")
    for stale in (snap, snap + ".bkup"):
        if os.path.exists(stale):
            os.remove(stale)  # a fresh scenario, not last window's file

    rng = np.random.default_rng(0)
    X = rng.standard_normal((3, 128)).astype(np.float32)
    y = 2.0 * np.cos(X[2]) + X[0] ** 2 - 0.5
    kw = dict(
        binary_operators=["+", "-", "*"], unary_operators=["cos"],
        npopulations=4, npop=24, ncycles_per_iteration=30, maxsize=12,
        seed=0, verbosity=0, progress=False,
    )
    baseline = sr.equation_search(X, y, niterations=2, **kw)

    t0 = time.perf_counter()
    set_fault_plan(FaultPlan(kind="raise", at=1))
    try:
        sup = supervised_search(
            X, y, niterations=2,
            snapshot_path=snap, snapshot_every_dispatches=1,
            max_attempts=3, backoff_base_s=0.05, backoff_jitter=0.0,
            telemetry=True, telemetry_dir=tele_d, **kw,
        )
    finally:
        clear_fault_plan()
    wall_s = time.perf_counter() - t0

    frontier = lambda r: [
        (c.complexity, float(c.loss), float(c.score), c.equation)
        for c in r.frontier()
    ]
    # newest log = the resumed, successful attempt; the faulted
    # attempt's verdict rides along from the supervisor's history
    report = analyze_run(resolve_log(tele_d))
    failed = sup.history[0] if sup.history else {}
    return [{
        "suite": "resilience",
        "case": "kill_resume_bit_identity",
        "ok": (
            frontier(baseline) == frontier(sup.result)
            and report["verdict"] == "healthy"
            # the closed loop is the contract: the interrupted
            # attempt's log must have read faulted+resumable, and the
            # recovery must have been exactly one resume
            and sup.attempts == 2
            and failed.get("verdict") == "faulted"
            and failed.get("resumable") is True
        ),
        "hof_bit_identical": frontier(baseline) == frontier(sup.result),
        "verdict": report["verdict"],
        "attempts": sup.attempts,
        "resumes": sup.resumes,
        "fault_error_type": failed.get("error_type"),
        "fault_verdict": failed.get("verdict"),
        "fault_resumable": failed.get("resumable"),
        "resumed_from_iteration": (
            (report.get("run", {}).get("resume_from") or {})
            .get("iteration")
        ),
        "search_wall_s": wall_s,
        "event_log": report.get("path"),
    }]


def bench_hostile_data():
    """Hostile-data hardening end to end (ISSUE 15): an adversarial
    fixture corpus — NaN-riddled rows, an Inf target cell, a constant
    target, 1e30-range features — must complete a real search under
    every data policy that admits it, with a FINITE hall of fame (the
    containment contract: non-finite never escapes a scoring epilogue),
    populated DatasetDiagnostics in the result AND the telemetry
    run_start event, and data_policy='reject' failing fast with the
    structured report instead of burning a search on poisoned data."""
    import symbolicregression_jl_tpu as sr
    from symbolicregression_jl_tpu.models.dataset import (
        HostileDatasetError,
    )
    from symbolicregression_jl_tpu.telemetry.analyze import (
        analyze_run,
        resolve_log,
    )

    rng = np.random.default_rng(0)
    X = rng.standard_normal((3, 128)).astype(np.float32)
    y = (2.0 * np.cos(X[2]) + X[0] ** 2 - 0.5).astype(np.float32)
    kw = dict(
        binary_operators=["+", "-", "*"], unary_operators=["cos"],
        npopulations=4, npop=24, ncycles_per_iteration=30, maxsize=12,
        niterations=2, seed=0, verbosity=0, progress=False,
        runtests=False,
    )

    corpus = {}
    Xn = X.copy(); Xn[0, :6] = np.nan; Xn[1, 40] = np.inf
    yn = y.copy(); yn[100] = np.inf
    corpus["nan_rows"] = (Xn, yn)
    corpus["constant_y"] = (X, np.full_like(y, 3.25))
    Xs = X.copy(); Xs[1] *= 1e30
    corpus["huge_scale"] = (Xs, y)

    out = []
    t0 = time.perf_counter()
    # reject fails fast on the non-finite corpus member, with the report
    try:
        sr.equation_search(*corpus["nan_rows"], data_policy="reject", **kw)
        rejected, report_rows = False, 0
    except HostileDatasetError as e:
        rejected = True
        report_rows = e.diagnostics.bad_rows
    out.append({
        "suite": "hostile_data",
        "case": "reject_fails_fast",
        "ok": rejected and report_rows == 8,
        "rejected": rejected,
        "bad_rows": report_rows,
    })

    for name, (Xc, yc) in sorted(corpus.items()):
        for policy in ("mask", "repair"):
            d = _suite_telemetry_dir(f"srtpu_suite_hostile_{name}_")
            res = sr.equation_search(
                Xc, yc, data_policy=policy, telemetry=True,
                telemetry_dir=d, **kw,
            )
            losses = [float(c.loss) for c in res.frontier()]
            diags = res.dataset_diagnostics or {}
            report = analyze_run(resolve_log(d))
            run_diags = (report.get("run") or {}).get(
                "dataset_diagnostics"
            ) or {}
            out.append({
                "suite": "hostile_data",
                "case": f"{name}_{policy}",
                "ok": (
                    bool(losses)
                    and all(np.isfinite(losses))
                    and diags.get("policy") == policy
                    and run_diags.get("policy") == policy
                    and report["verdict"] in ("healthy", "stalled")
                ),
                "hof_size": len(losses),
                "hof_finite": bool(losses) and all(np.isfinite(losses)),
                "best_loss": min(losses) if losses else None,
                "masked_rows": diags.get("masked_rows"),
                "repaired_cells": diags.get("repaired_cells"),
                "warnings": len(diags.get("warnings") or []),
                "run_start_diagnostics": bool(run_diags),
                "verdict": report["verdict"],
                "nonfinite_fraction": report.get("nonfinite_fraction"),
            })
    out[-1]["seconds"] = time.perf_counter() - t0
    return out


def bench_fleet():
    """Fleet observability end to end (ISSUE 13): two real tiny
    searches write telemetry into one fleet root; the fleet scanner
    must index BOTH as healthy rows in fleet_index.json, the
    OpenMetrics exposition of that index must pass the self-check
    validator, and `scripts/srfleet.py --once` must exit 0 on the clean
    fleet and nonzero after a stalled run is injected — the exit code
    matches the alert state, which is the whole CI contract."""
    import subprocess
    import tempfile

    import symbolicregression_jl_tpu as sr
    from symbolicregression_jl_tpu.telemetry.export import (
        render_openmetrics,
        validate_exposition,
    )
    from symbolicregression_jl_tpu.telemetry.fleet import FleetScanner

    root = tempfile.mkdtemp(prefix="srtpu_suite_fleet_")
    rng = np.random.default_rng(0)
    X = rng.standard_normal((3, 128)).astype(np.float32)
    y = 2.0 * np.cos(X[2]) + X[0] ** 2 - 0.5
    t0 = time.perf_counter()
    for i, seed in enumerate((0, 1)):
        sr.equation_search(
            X, y,
            binary_operators=["+", "-", "*"], unary_operators=["cos"],
            npopulations=4, npop=24, ncycles_per_iteration=30,
            maxsize=12, niterations=2, seed=seed, verbosity=0,
            progress=False,
            telemetry=True, telemetry_dir=os.path.join(root, f"run{i}"),
        )
    wall_s = time.perf_counter() - t0

    index = FleetScanner(root).refresh()
    rows = index["runs"]
    rows_ok = len(rows) == 2 and all(
        r["verdict"] == "healthy" for r in rows
    )
    text = render_openmetrics(fleet_index=index)
    problems = validate_exposition(text)

    srfleet = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "srfleet.py",
    )
    run_once = lambda: subprocess.run(
        [sys.executable, srfleet, root, "--once"],
        capture_output=True, text=True, timeout=300,
    ).returncode
    rc_clean = run_once()
    # inject a stalled run (flat best loss, collapsed diversity over
    # more than the doctor's stall window) — the stalled_run alert must
    # fire and flip srfleet's exit code
    stall_dir = os.path.join(root, "stalled")
    os.makedirs(stall_dir, exist_ok=True)
    with open(
        os.path.join(stall_dir, "events-stalled.jsonl"), "w"
    ) as f:
        ev = {"v": 1, "run": "stalled-run", "type": "run_start",
              "t": 1.0, "run_id": "stalled-run", "attempt": 1,
              "config_fingerprint": "x", "backend": "cpu",
              "devices": ["TFRT_CPU_0"], "nout": 1}
        f.write(json.dumps(ev) + "\n")
        for i in range(8):
            f.write(json.dumps({
                "v": 1, "run": "stalled-run", "type": "metrics",
                "t": 2.0 + i, "output": 0, "iteration": i,
                "snapshot": {"counters": {}, "histograms": {},
                             "gauges": {"best_loss": 1.0,
                                        "population_diversity": 0.05}},
            }) + "\n")
        f.write(json.dumps({
            "v": 1, "run": "stalled-run", "type": "run_end", "t": 11.0,
            "num_evals": 100.0, "search_time_s": 10.0,
        }) + "\n")
    rc_alert = run_once()
    return [{
        "suite": "fleet",
        "case": "two_searches_one_root",
        "ok": (
            rows_ok and not problems
            and rc_clean == 0 and rc_alert != 0
        ),
        "index_rows": len(rows),
        "verdicts": [r["verdict"] for r in rows],
        "exposition_ok": not problems,
        "exposition_problems": problems[:3],
        "srfleet_rc_clean": rc_clean,
        "srfleet_rc_with_stall": rc_alert,
        "search_wall_s": wall_s,
        "fleet_root": root,
    }]


def bench_serving():
    """srserve end to end (ISSUE 16): four same-shape jobs through the
    JobServer at max_tenants=2 — two dispatches of one bucket, the
    second a warm compile hit. Every job must complete with a
    finite-loss frontier, the warm-hit rate must be positive after the
    first bucket, the per-job run ids must land in the fleet registry,
    and the srtpu_serve_* exposition must pass the validator. Reports
    jobs/s against the solo per-job wall — the number batching is
    supposed to move."""
    import tempfile

    from symbolicregression_jl_tpu.serving import JobServer
    from symbolicregression_jl_tpu.telemetry.export import (
        render_openmetrics,
        validate_exposition,
    )
    from symbolicregression_jl_tpu.telemetry.fleet import load_registry
    from symbolicregression_jl_tpu.telemetry.metrics import (
        MetricsRegistry,
    )

    root = tempfile.mkdtemp(prefix="srtpu_suite_serving_")
    registry = MetricsRegistry()
    server = JobServer(
        niterations=2, max_tenants=2, flush_timeout_s=600.0,
        fleet_root=root, registry=registry,
        binary_operators=["+", "-", "*"], unary_operators=["cos"],
        npop=24, npopulations=2, ncycles_per_iteration=30,
        maxsize=12, seed=0, verbosity=0, progress=False,
    )
    rng = np.random.default_rng(0)
    n_jobs = 4
    for i in range(n_jobs):
        X = rng.standard_normal((2, 100)).astype(np.float32)
        y = X[0] * X[0] + (i + 1) * np.cos(X[1])
        server.submit(X, y, job_id=f"suite-{i}", seed=i)

    t0 = time.perf_counter()
    done = server.drain()
    wall_s = time.perf_counter() - t0

    finite = [
        bool(j.result.frontier())
        and np.isfinite(min(c.loss for c in j.result.frontier()))
        for j in done
    ]
    stats = server.stats()
    text = render_openmetrics(registry=registry)
    problems = validate_exposition(text)
    registered = sorted(
        r.get("run_id") for r in load_registry(root)
    )
    ok = (
        len(done) == n_jobs
        and all(finite)
        and stats["warm_hit_rate"] > 0
        and registered == sorted(f"suite-{i}" for i in range(n_jobs))
        and not problems
    )
    return [{
        "suite": "serving",
        "case": "warm_bucket_4_jobs",
        "ok": ok,
        "jobs": len(done),
        "jobs_per_s": len(done) / wall_s if wall_s > 0 else None,
        "dispatches": stats["dispatches"],
        "warm_hit_rate": stats["warm_hit_rate"],
        "all_finite": all(finite),
        "registered_runs": len(registered),
        "exposition_ok": not problems,
        "exposition_problems": problems[:3],
        "wall_s": wall_s,
        "fleet_root": root,
    }]


def bench_multichip():
    """Multi-chip island sharding (ISSUE 9): the REAL production
    `equation_search` sharded over an 8-virtual-device (islands, rows)
    mesh vs the same search on one device — benchmark/multichip.py in
    its own subprocess (the capture forces 8 host CPU devices, which
    must happen before ITS backend initializes, not ours). Reports
    trees-rows/s both ways, speedup vs the 1-device wall clock, the
    hall-of-fame bit-identity verdict, and the sharded-carry verdict
    (every IslandState leaf island-sharded after the run)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from multichip import run_subprocess

    rows, error = run_subprocess(timeout=900)
    if error is not None:
        return [{"suite": "multichip", "error": f"capture {error}"}]
    return rows


def bench_search_iteration():
    """Full-search throughput: one jitted evolution iteration (s_r_cycle +
    simplify + constant-opt + HoF merge + migration) over all islands —
    the analog of the reference's 'cycles per second' runtime print
    (src/SymbolicRegression.jl:869-896). Reported as candidate evaluations
    per second: ncycles x n_parallel_tournaments x islands / time."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from symbolicregression_jl_tpu.api import _make_init_fn, _make_iteration_fn
    from symbolicregression_jl_tpu.models.options import make_options

    options = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        npop=33,
        npopulations=15,
        ncycles_per_iteration=100,
        maxsize=20,
    )
    n_feat, n_rows = 5, 256
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((n_feat, n_rows)), jnp.float32)
    y = 2.0 * jnp.cos(X[4]) + X[1] ** 2 - 2.0
    baseline = jnp.float32(float(jnp.var(y)))

    init_fn = _make_init_fn(options, n_feat, False)
    scalars = options.traced_scalars()
    states = init_fn(
        jax.random.split(jax.random.PRNGKey(0), options.npopulations),
        X, y, baseline, scalars,
    )
    it_fn = _make_iteration_fn(options, False)
    cm = jnp.int32(options.maxsize)

    def run():
        s2, ghof = it_fn(
            states, jax.random.PRNGKey(1), cm, X, y, baseline, scalars
        )
        jax.block_until_ready(ghof.losses)

    dt = _median_time(run, reps=3)
    cand_evals = (
        options.ncycles_per_iteration
        * options.n_parallel_tournaments
        * options.npopulations
    )
    return [
        {
            "suite": "search_iteration",
            "case": (
                f"islands{options.npopulations}_npop{options.npop}_"
                f"cycles{options.ncycles_per_iteration}_rows{n_rows}"
            ),
            "median_s": dt,
            "candidate_evals_per_s": cand_evals / dt,
        }
    ]


def bench_search_iteration_northstar():
    """BASELINE.json's north-star search shape (npopulations=64,
    npop=1000): at this scale the in-loop scoring batches clear
    _PALLAS_MIN_WORK (the trees x rows volume gate), so on TPU the
    evolution cycles themselves run
    through the Pallas eval kernel and constant optimization through the
    fused loss/grad kernels (optimizer_backend='auto'). Heavy — runs on
    non-CPU platforms or with SRTPU_SUITE_BIG=1.

    Measurement order is fault-aware (r04: the fused single-call form is
    the only program shape that has ever faulted the chip, and a faulted
    client wedges its process): the CHUNKED-dispatch form
    (max_cycles_per_dispatch=5, numerically identical — see
    tests/test_dispatch_chunking.py) runs FIRST, then the optimizer-off
    breakdown (also chunked), and the fused single-call attempt runs
    LAST so its fault cannot blank the rows before it. Each entry is
    printed by the runner as soon as its sub-measurement returns."""
    import jax

    if jax.devices()[0].platform == "cpu" and not os.environ.get(
        "SRTPU_SUITE_BIG"
    ):
        return [_emit({
            "suite": "search_iteration_northstar",
            "skipped": "cpu platform (set SRTPU_SUITE_BIG=1 to force)",
        }, [])]
    import jax.numpy as jnp
    import numpy as np

    from symbolicregression_jl_tpu.api import (
        _make_init_fn,
        _make_iteration_driver,
    )
    from symbolicregression_jl_tpu.models.options import make_options

    shape_kwargs = dict(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        npop=1000,
        npopulations=64,
        ncycles_per_iteration=25,
        maxsize=20,
    )
    options = make_options(**shape_kwargs)
    n_feat, n_rows = 1, 1000
    rng = np.random.default_rng(0)
    theta = rng.uniform(1.0, 3.0, n_rows).astype(np.float32)
    X = jnp.asarray(theta[None, :])
    y = jnp.asarray(
        (np.exp(-(theta**2) / 2.0) / np.sqrt(2 * np.pi)).astype(np.float32)
    )
    baseline = jnp.float32(float(jnp.var(y)))

    init_fn = _make_init_fn(options, n_feat, False)
    scalars = options.traced_scalars()
    states = init_fn(
        jax.random.split(jax.random.PRNGKey(0), options.npopulations),
        X, y, baseline, scalars,
    )
    cm = jnp.int32(options.maxsize)
    case = (
        f"islands{options.npopulations}_npop{options.npop}_"
        f"cycles{options.ncycles_per_iteration}_rows{n_rows}"
    )
    cand_evals = (
        options.ncycles_per_iteration
        * options.n_parallel_tournaments
        * options.npopulations
    )

    def _time_variant(opts):
        it = _make_iteration_driver(opts, False)
        sc = opts.traced_scalars()

        def run():
            s2, ghof = it(
                states, jax.random.PRNGKey(1), cm, X, y, baseline, sc
            )
            jax.block_until_ready(ghof.losses)

        return _median_time(run, reps=3)

    out = []
    dt_chunked = None
    variants = [
        ("chunked5", dict(max_cycles_per_dispatch=5)),
        ("chunked5_no_optimizer", dict(
            max_cycles_per_dispatch=5, should_optimize_constants=False
        )),
        ("fused", {}),
    ]
    for dispatch, extra in variants:
        try:
            dt = _time_variant(make_options(**shape_kwargs, **extra))
        except Exception as e:
            _emit({
                "suite": "search_iteration_northstar",
                "case": case,
                "dispatch": dispatch,
                "error": f"{type(e).__name__}: {str(e)[:200]}",
            }, out)
            continue
        if dispatch == "chunked5":
            dt_chunked = dt
        _emit({
            "suite": "search_iteration_northstar",
            "case": case,
            "dispatch": dispatch,
            "median_s": dt,
            "candidate_evals_per_s": cand_evals / dt,
        }, out)
        if dispatch == "chunked5_no_optimizer" and dt_chunked:
            _emit({
                "suite": "search_iteration_northstar",
                "case": "breakdown",
                "dispatch": "chunked5",
                "full_s": dt_chunked,
                "no_optimizer_s": dt,
                "bfgs_share": max(0.0, 1.0 - dt / dt_chunked),
            }, out)
    return out


def bench_precision_ratio():
    """float64 vs float32 population-scoring throughput on one workload.

    The reference's default dtype is Float64 with native-speed fused eval
    (reference src/InterfaceDynamicExpressions.jl:50-52); here f64 routes
    to the lockstep jnp interpreter (the Pallas kernel is f32/bf16-only —
    no native f64 on v5e), so this entry publishes the measured cost of
    choosing precision='float64'. Runs LAST: jax_enable_x64 is
    process-global."""
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.models.fitness import score_trees
    from symbolicregression_jl_tpu.models.mutate_device import (
        gen_random_tree_fixed_size,
    )
    from symbolicregression_jl_tpu.models.options import make_options

    n_trees, n_rows = 2048, 1000
    options = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        maxsize=20,
    )
    sizes = jax.random.randint(jax.random.PRNGKey(1), (n_trees,), 3, 20)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(
            k, s, 2, options.operators, options.max_len
        )
    )(jax.random.split(jax.random.PRNGKey(0), n_trees), sizes)
    rng = np.random.default_rng(0)
    X_h = rng.standard_normal((2, n_rows))
    y_h = 2.0 * np.cos(X_h[1]) + X_h[0] ** 2

    out = []
    rates = {}
    for name, ftype in (("float32", np.float32), ("float64", np.float64)):
        X = jnp.asarray(X_h.astype(ftype))
        y = jnp.asarray(y_h.astype(ftype))
        t = trees._replace(cval=trees.cval.astype(X.dtype))
        bl = jnp.asarray(np.var(y_h).astype(ftype))
        f = jax.jit(
            lambda t, X, y, bl: score_trees(t, X, y, None, bl, options)
        )
        f(t, X, y, bl)
        dt = _median_time(
            lambda: jax.block_until_ready(f(t, X, y, bl)), reps=3
        )
        rates[name] = n_trees * n_rows / dt
        out.append(
            {
                "suite": "precision_ratio",
                "case": name,
                "median_s": dt,
                "trees_rows_per_s": rates[name],
            }
        )
    out.append(
        {
            "suite": "precision_ratio",
            "case": "f32_over_f64",
            "ratio": rates["float32"] / rates["float64"],
        }
    )
    return out


def bench_fitness_cache():
    """Evaluation memo bank (ISSUE 1): a seeded search with
    cache_fitness=True, reporting per-iteration unique-ratio, memo hit
    rate and eval-batch shrinkage, cached-vs-uncached wall time, and the
    bit-identical hall-of-fame check (docs/memo_bank.md guarantee)."""
    import symbolicregression_jl_tpu as sr

    rng = np.random.default_rng(0)
    X = rng.standard_normal((3, 256)).astype(np.float32)
    y = 2.0 * np.cos(X[2]) + X[0] ** 2 - 0.5
    kw = dict(
        binary_operators=["+", "-", "*"], unary_operators=["cos"],
        npopulations=6, npop=33, ncycles_per_iteration=80, maxsize=16,
        seed=3, verbosity=0, progress=False, niterations=5,
    )
    t0 = time.perf_counter()
    r_off = sr.equation_search(X, y, **kw)
    uncached_s = time.perf_counter() - t0
    sr.clear_memo_banks()  # cold bank: measure warm-up behavior too
    t0 = time.perf_counter()
    r_on = sr.equation_search(X, y, cache_fitness=True, **kw)
    cached_s = time.perf_counter() - t0

    frontier = lambda r: [
        (c.complexity, float(c.loss), float(c.score), c.equation)
        for c in r.frontier()
    ]
    out = []
    for row in r_on.cache_stats["per_iteration"]:
        out.append({
            "suite": "fitness_cache",
            "case": f"iteration{row['iteration'] + 1}",
            "scored": row["scored"],
            "unique": row["unique"],
            "memo_hits": row["memo_hits"],
            "evaluated": row["evaluated"],
            "unique_ratio": row["unique_ratio"],
            "memo_hit_rate": row["memo_hit_rate"],
            # 1 - fill = eval-batch shrinkage the dedup realized
            "eval_batch_fill": row["eval_batch_fill"],
        })
    out.append({
        "suite": "fitness_cache",
        "case": "summary",
        "hof_identical": frontier(r_off) == frontier(r_on),
        "uncached_s": uncached_s,
        "cached_s": cached_s,
        **r_on.cache_stats["totals"],
    })
    return out


def bench_static_analysis():
    """Static-analysis gate as a suite case (ISSUEs 3+4): srlint
    violation count, compile-surface baseline status, the srmem
    HBM-footprint gate, the srkey Options-contract gate, the srshard
    sharding-contract gate, and docs/api_reference.md drift, via
    scripts/lint.py --format json in its own subprocess (the gate pins
    CPU for itself; this case never needs the device)."""
    import subprocess

    script = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "lint.py",
    )
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, script, "--format", "json"],
            capture_output=True, text=True, timeout=2700,
        )
    except subprocess.TimeoutExpired:
        return [{
            "suite": "static_analysis",
            "error": "lint.py timed out after 2700s",
            "seconds": round(time.time() - t0, 1),
        }]
    seconds = round(time.time() - t0, 1)
    try:
        payload = json.loads(proc.stdout)
    except json.JSONDecodeError:
        tail = (proc.stderr or proc.stdout).strip().splitlines()[-2:]
        return [{
            "suite": "static_analysis",
            "error": f"lint.py rc={proc.returncode}: "
                     + " / ".join(tail)[:200],
            "seconds": seconds,
        }]
    surface = payload.get("surface") or {}
    memory = payload.get("memory") or {}
    cost = payload.get("cost") or {}
    keys = payload.get("keys") or {}
    shard = payload.get("shard") or {}
    docs = payload.get("docs") or {}
    tele = payload.get("telemetry_schema") or {}
    mem_configs = memory.get("configs", {})
    cost_configs = cost.get("configs", {})
    return [
        {
            "suite": "static_analysis",
            "case": "srlint",
            "ok": not payload.get("counts"),
            "violations": sum(payload.get("counts", {}).values()),
            "suppressed": payload.get("suppressed", 0),
        },
        {
            "suite": "static_analysis",
            "case": "compile_surface",
            "ok": surface.get("ok", False),
            "configs": len(surface.get("configs", {})),
            "baseline_match": surface.get("baseline_match", False),
            "problems": len(surface.get("problems", [])),
        },
        {
            "suite": "static_analysis",
            "case": "srmem",
            "ok": memory.get("ok", False),
            "configs": len(mem_configs),
            "baseline_match": memory.get("baseline_match", False),
            "problems": len(memory.get("problems", [])),
            # worst modeled resident footprint across the matrix, the
            # number the HBM budget gates on
            "max_footprint_mb": round(max(
                (e.get("footprint_bytes", 0) for e in mem_configs.values()),
                default=0,
            ) / 1e6, 2),
            "hbm_budget_gb": memory.get("hbm_budget_gb", 0),
        },
        {
            "suite": "static_analysis",
            "case": "srcost",
            "ok": cost.get("ok", False),
            "configs": len(cost_configs),
            "baseline_match": cost.get("baseline_match", False),
            "problems": len(cost.get("problems", [])),
            # headline modeled numbers of the base config — the
            # per-dispatch cost the baseline gates on
            "base_flops": (cost_configs.get("base") or {}).get("flops"),
            "base_padded_waste": (
                cost_configs.get("base") or {}
            ).get("padded_waste_fraction"),
        },
        {
            "suite": "static_analysis",
            "case": "srkey",
            "ok": keys.get("ok", False),
            "fields": sum((keys.get("fields") or {}).values()),
            "problems": len(keys.get("problems", [])),
            # both trace configs orchestration-invariant = the warm-
            # compile sharing contract the serving tier relies on holds
            "orchestration_invariant": all(
                e.get("orchestration_invariant", False)
                for e in (keys.get("configs") or {}).values()
            ) if keys.get("traced") else None,
        },
        {
            "suite": "static_analysis",
            "case": "srshard",
            "ok": shard.get("ok", False),
            "configs": len(shard.get("configs", {})),
            "baseline_match": shard.get("baseline_match", False),
            "problems": len(shard.get("problems", [])),
            # the three headline invariants the sharding contract gates
            # on: no collective crosses a tenant boundary, no carry leaf
            # silently replicates, and the modeled comms share of the
            # worst stage stays a fraction (not the bottleneck)
            "cross_tenant_collectives": shard.get(
                "cross_tenant_collectives"
            ),
            "max_replication_factor": shard.get(
                "max_replication_factor"
            ),
            "comms_fraction": shard.get("comms_fraction"),
        },
        {
            "suite": "static_analysis",
            "case": "api_reference_drift",
            "ok": docs.get("api_reference_current", False),
        },
        {
            "suite": "static_analysis",
            "case": "telemetry_schema",
            "ok": tele.get("ok", False),
            "events": tele.get("events", 0),
        },
        {
            "suite": "static_analysis",
            "case": "fleet_exposition",
            "ok": (payload.get("fleet_exposition") or {}).get(
                "ok", False
            ),
            "samples": (payload.get("fleet_exposition") or {}).get(
                "samples", 0
            ),
        },
        {
            "suite": "static_analysis",
            "case": "summary",
            "ok": payload.get("ok", False),
            "rc": proc.returncode,
            "seconds": seconds,
        },
    ]


def bench_pallas_bucketed():
    """Bucket-laddered Pallas kernel correctness (ISSUE 17): the bucketed
    kernel dispatch vs the flat kernel under Pallas interpret mode on
    CPU, on a skewed-length batch — values, ok mask, AND poison
    semantics (planted inf constants) must be bit-identical, plus the
    fused loss epilogue vs its host-graph twin
    (aggregate_loss(tile_rows=r_block) + contain_nonfinite, both sides
    jitted). Interpret mode executes the same kernel program the TPU
    runs, minus the Mosaic schedule, so this is the portable half of
    the bucketed-vs-flat acceptance; the on-chip throughput half lives
    in bench.py / kernel_tune.py. Small shapes: interpret mode pays
    ~1000x per slot."""
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.models.mutate_device import (
        gen_random_tree_fixed_size,
    )
    from symbolicregression_jl_tpu.models.options import make_options
    from symbolicregression_jl_tpu.ops.losses import (
        aggregate_loss,
        contain_nonfinite,
    )
    from symbolicregression_jl_tpu.ops.pallas_eval import (
        eval_loss_trees_pallas,
        eval_trees_pallas,
    )

    t0 = time.time()
    options = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        maxsize=20,
    )
    ops = options.operators
    loss_fn = options.elementwise_loss
    n_trees, n_rows = 48, 300
    rng = np.random.default_rng(0)
    u = rng.random(n_trees)
    sizes = np.where(
        u < 0.80, rng.integers(3, 7, n_trees),
        np.where(u < 0.95, rng.integers(7, 13, n_trees),
                 rng.integers(13, 21, n_trees)),
    ).astype(np.int32)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(
            k, s, 3, ops, options.max_len
        )
    )(jax.random.split(jax.random.PRNGKey(0), n_trees), jnp.asarray(sizes))
    X = jax.random.normal(jax.random.PRNGKey(2), (3, n_rows), jnp.float32)
    y = 2.0 * jnp.cos(X[2]) + X[1] ** 2 - 2.0
    ladder = (0.25, 0.5, 1.0)  # skewed 3-bucket ladder
    kw = dict(t_block=8, r_block=128, interpret=True)

    y_flat, ok_flat = eval_trees_pallas(trees, X, ops, **kw)
    y_buck, ok_buck = eval_trees_pallas(
        trees, X, ops, bucket_ladder=ladder, **kw
    )
    values_ok = bool(np.array_equal(
        np.asarray(y_flat), np.asarray(y_buck), equal_nan=True
    ))
    mask_ok = bool(np.array_equal(np.asarray(ok_flat), np.asarray(ok_buck)))

    # poison semantics: planted inf constants must poison the SAME trees
    poisoned = trees._replace(cval=jnp.where(
        jnp.arange(n_trees)[:, None] % 7 == 0, jnp.inf, trees.cval
    ))
    yp_flat, okp_flat = eval_trees_pallas(poisoned, X, ops, **kw)
    yp_buck, okp_buck = eval_trees_pallas(
        poisoned, X, ops, bucket_ladder=ladder, **kw
    )
    poison_ok = bool(
        np.array_equal(np.asarray(yp_flat), np.asarray(yp_buck),
                       equal_nan=True)
        and np.array_equal(np.asarray(okp_flat), np.asarray(okp_buck))
    )

    # fused epilogue vs the host-graph twin, both sides jitted (the
    # eager host graph compiles a true divide where jit folds the
    # constant divisor to a reciprocal-multiply — the production
    # composition is always jitted, so that is the contract surface)
    @jax.jit
    def host_twin(t):
        yp, ok = eval_trees_pallas(t, X, ops, **kw)
        elem = loss_fn(yp, y[None, :])
        return contain_nonfinite(
            aggregate_loss(elem, None, tile_rows=kw["r_block"]), ok
        )

    fused = eval_loss_trees_pallas(
        trees, X, y, ops, loss_fn, bucket_ladder=ladder, **kw
    )
    fused_ok = bool(np.array_equal(
        np.asarray(fused), np.asarray(host_twin(trees)), equal_nan=True
    ))
    return [
        {
            "suite": "pallas_bucketed",
            "case": "summary",
            "bit_identical_values": values_ok,
            "bit_identical_ok": mask_ok,
            "bit_identical_poison": poison_ok,
            "fused_bit_identical": fused_ok,
            "ladder": list(ladder),
            "seconds": round(time.time() - t0, 1),
        }
    ]


# (fn, per-case subprocess timeout). northstar LAST: it is the one case
# with a device-fault history (r04/r03), and even in its own process it
# is the longest.
_CASES = [
    (bench_static_analysis, 2900),
    (bench_eval_fixed_tree, 600),
    (bench_single_eval_48_nodes, 600),
    (bench_population_scoring, 600),
    (bench_bucketed_eval, 900),
    (bench_pallas_bucketed, 900),
    (bench_multichip, 1200),
    (bench_telemetry, 900),
    (bench_run_doctor, 900),
    (bench_profile, 900),
    (bench_resilience, 900),
    (bench_hostile_data, 900),
    (bench_fleet, 1200),
    (bench_serving, 1200),
    (bench_search_iteration, 1200),
    (bench_fitness_cache, 1200),
    (bench_precision_ratio, 1200),
    (bench_search_iteration_northstar, 4800),
]
_CASE_BY_NAME = {fn.__name__: (fn, t) for fn, t in _CASES}


def _run_case_inline(fn):
    """Run one case in THIS process, emitting rows incrementally."""
    try:
        rows = fn()
    except Exception as e:  # pragma: no cover
        print(f"# {fn.__name__} failed: {e}", file=sys.stderr)
        _emit(
            {
                "suite": fn.__name__.removeprefix("bench_"),
                "error": f"{type(e).__name__}: {str(e)[:200]}",
            },
            [],
        )
        return
    for r in rows:
        # northstar emits its own rows incrementally; everything else
        # returns them. _emit de-dups nothing, so emit only rows that
        # did not already go through it (they carry the platform stamp).
        if "platform" not in r:
            _emit(r, [])


def main():
    import argparse
    import subprocess

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--case", default=None, help="child mode: one case")
    ap.add_argument(
        "--in-process", action="store_true",
        help="run all cases in this process (no subprocess isolation)",
    )
    ap.add_argument(
        "--isolate", action="store_true",
        help="(default behavior; flag exists so the watcher's argv "
        "records distinguish the isolated suite from pre-r5 captures)",
    )
    ns = ap.parse_args()

    if ns.case or ns.in_process:
        # child / legacy mode: this process owns the device
        from bench import _devices_or_cpu_fallback

        devices = _devices_or_cpu_fallback(verbose=True, use_memo=True)
        _EMIT_PLATFORM[0] = devices[0].platform
        if ns.case:
            fn, _ = _CASE_BY_NAME[ns.case]
            _run_case_inline(fn)
        else:
            # in-process: precision_ratio LAST — it flips the
            # process-global jax_enable_x64 (subprocess isolation is
            # what normally contains that)
            ordered = sorted(
                _CASES, key=lambda c: c[0] is bench_precision_ratio
            )
            for fn, _ in ordered:
                _run_case_inline(fn)
        return

    # parent mode (default): one FRESH subprocess per case so a device
    # fault (a faulted axon client wedges its process) costs exactly one
    # case's rows, never the window's (VERDICT r4 weak #1: r04's
    # northstar fault blanked precision_ratio). The parent deliberately
    # never initializes jax — the tunnel has one slot and each child
    # needs it.
    script = os.path.abspath(__file__)
    for fn, timeout in _CASES:
        t0 = time.time()
        # own process GROUP + killpg on timeout (same guard as
        # scale_fault_bisect._run_stage / bench._probe_tpu_subprocess):
        # a wedged axon client's helper processes must not keep holding
        # the tunnel's one slot after the case is given up on
        p = subprocess.Popen(
            [sys.executable, script, "--case", fn.__name__],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=os.path.dirname(os.path.dirname(script)),
            start_new_session=True,
        )
        # Stream-forward the child's stdout LINE BY LINE instead of
        # buffering via communicate(): rows a case emitted before a
        # mid-case kill (watcher window close, this parent's own
        # timeout) are already part of the record instead of dying in
        # the pipe. The reader threads keep both pipes drained (no
        # deadlock on a full stderr buffer); the timeout wraps the
        # readline loops via p.wait.
        import threading

        emitted = [0]
        err_lines = []
        err_frozen = threading.Event()

        def _pump_stdout(stream=p.stdout):
            for line in stream:
                line = line.strip()
                # forward the child's JSON rows verbatim (they are the
                # record)
                if line.startswith("{") and line.endswith("}"):
                    print(line, flush=True)
                    emitted[0] += 1
                elif line.startswith("#"):
                    print(line, file=sys.stderr)

        def _pump_stderr(stream=p.stderr):
            for line in stream:
                if not err_frozen.is_set():
                    err_lines.append(line.rstrip("\n"))

        t_out = threading.Thread(target=_pump_stdout, daemon=True)
        t_err = threading.Thread(target=_pump_stderr, daemon=True)
        t_out.start()
        t_err.start()
        timed_out = False
        try:
            rc = p.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            import signal as _signal

            try:
                os.killpg(p.pid, _signal.SIGKILL)
            except Exception:
                p.kill()
            try:
                p.wait(timeout=10)
            except Exception:
                pass
            rc, timed_out = -9, True
        # helper grandchildren may inherit the pipes and keep them open
        # after the kill: bounded joins, never a hang
        t_out.join(timeout=10)
        t_err.join(timeout=10)
        if timed_out:
            # AFTER the joins AND with the pump frozen: a grandchild
            # that kept the pipe open past the bounded join must not
            # push the kill reason out of the reported 2-line tail
            err_frozen.set()
            err_lines[:] = ["timeout"]
        err = "\n".join(err_lines)
        emitted = emitted[0]
        if rc != 0:
            tail = [ln for ln in (err or "").splitlines() if ln.strip()][-2:]
            print(json.dumps({
                "suite": fn.__name__.removeprefix("bench_"),
                "error": f"case subprocess rc={rc}: "
                         + " / ".join(tail)[:200],
                "seconds": round(time.time() - t0, 1),
            }), flush=True)
        elif emitted == 0:
            print(json.dumps({
                "suite": fn.__name__.removeprefix("bench_"),
                "error": "case subprocess produced no rows",
            }), flush=True)


if __name__ == "__main__":
    main()
