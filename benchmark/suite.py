"""Benchmark suite mirroring the reference's benchmark infrastructure
(reference benchmark/benchmarks.jl:1-29 `SUITE["evaluation"]` and
benchmark/single_eval.jl:1-28), plus the framework's own batched-population
shapes. Prints one JSON line per entry.

Usage:
    python benchmark/suite.py            # run on the default backend
    JAX_PLATFORMS=cpu python benchmark/suite.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _median_time(fn, reps=5):
    fn()  # warmup/compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def bench_eval_fixed_tree():
    """The reference's SUITE["evaluation"]: a fixed 15-node tree over
    X = 5x1000, Float32/Float64 (BigFloat has no TPU analog; bfloat16 is
    the TPU-native third precision)."""
    import jax
    import jax.numpy as jnp

    import symbolicregression_jl_tpu as sr

    ops = sr.make_operator_set(["+", "-", "/", "*"], ["cos", "exp"])
    # same topology as benchmark/benchmarks.jl:7-19:
    # (cos(1.0+x1)*exp(-1.0) stacked into +/- and * / branches over x2/x3)
    s = ("((cos(1 + x1) * exp(-1)) - (x2 / x3)) + "
         "((cos(1 + x1) * exp(-1)) * (x2 / x3))")
    expr = sr.parse_expression(s, ops)
    rng = np.random.default_rng(0)
    X_h = rng.standard_normal((5, 1000))

    out = []
    for dtype_name, dtype in [
        ("float32", jnp.float32),
        ("bfloat16", jnp.bfloat16),
    ]:
        tree = jax.tree_util.tree_map(
            jnp.asarray, sr.encode_tree(expr, 24)
        )
        tree = tree._replace(cval=tree.cval.astype(dtype))
        X = jnp.asarray(X_h, dtype)
        f = jax.jit(lambda t, X: sr.eval_tree(t, X, ops))
        y, ok = f(tree, X)
        dt = _median_time(lambda: jax.block_until_ready(f(tree, X)))
        out.append(
            {
                "suite": "evaluation",
                "case": dtype_name,
                "tree_nodes": int(tree.length),
                "rows": 1000,
                "median_s": dt,
            }
        )
    return out


def bench_single_eval_48_nodes():
    """The reference's single_eval.jl micro: 48-node tree on 3x200."""
    import jax
    import jax.numpy as jnp

    import symbolicregression_jl_tpu as sr

    ops = sr.make_operator_set(["+", "*", "/", "-"], ["cos", "sin"])
    s = (
        "((x1 + x1) * ((-0.5982493 / x0) / -0.54734415)) + "
        "(sin(cos(sin(1.2926733 - 1.6606787) / "
        "sin(((0.14577048 * x0) + ((0.111149654 + x0) - -0.8298334)) "
        "- -1.2071426)) * (cos(x2 - 2.3201916) + ((x0 - (x0 * x1)) / x1)))"
        " / (0.14854191 - ((cos(x1) * -1.6047639) - 0.023943262)))"
    )
    expr = sr.parse_expression(s, ops)
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((3, 200)), jnp.float32)
    tree = jax.tree_util.tree_map(jnp.asarray, sr.encode_tree(expr, 56))
    f = jax.jit(lambda t, X: sr.eval_tree(t, X, ops))
    f(tree, X)
    dt = _median_time(lambda: jax.block_until_ready(f(tree, X)))
    return [
        {
            "suite": "single_eval",
            "case": "48_nodes_3x200",
            "tree_nodes": int(tree.length),
            "median_s": dt,
        }
    ]


def bench_population_scoring():
    """This framework's own shape: whole-population fused scoring (the
    per-cycle hot call of the evolution engine)."""
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.models.fitness import score_trees
    from symbolicregression_jl_tpu.models.mutate_device import (
        gen_random_tree_fixed_size,
    )
    from symbolicregression_jl_tpu.models.options import make_options

    options = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        maxsize=20,
    )
    n_trees, n_rows = 4096, 1000
    sizes = jax.random.randint(jax.random.PRNGKey(1), (n_trees,), 3, 20)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(
            k, s, 5, options.operators, options.max_len
        )
    )(jax.random.split(jax.random.PRNGKey(0), n_trees), sizes)
    X = jax.random.normal(jax.random.PRNGKey(2), (5, n_rows), jnp.float32)
    y = 2.0 * jnp.cos(X[4]) + X[1] ** 2 - 2.0

    f = jax.jit(
        lambda t: score_trees(t, X, y, None, jnp.float32(1.0), options)
    )
    f(trees)
    dt = _median_time(lambda: jax.block_until_ready(f(trees)))
    return [
        {
            "suite": "population_scoring",
            "case": f"{n_trees}x{n_rows}",
            "median_s": dt,
            "trees_rows_per_s": n_trees * n_rows / dt,
        }
    ]


def bench_search_iteration():
    """Full-search throughput: one jitted evolution iteration (s_r_cycle +
    simplify + constant-opt + HoF merge + migration) over all islands —
    the analog of the reference's 'cycles per second' runtime print
    (src/SymbolicRegression.jl:869-896). Reported as candidate evaluations
    per second: ncycles x n_parallel_tournaments x islands / time."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from symbolicregression_jl_tpu.api import _make_init_fn, _make_iteration_fn
    from symbolicregression_jl_tpu.models.options import make_options

    options = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        npop=33,
        npopulations=15,
        ncycles_per_iteration=100,
        maxsize=20,
    )
    n_feat, n_rows = 5, 256
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((n_feat, n_rows)), jnp.float32)
    y = 2.0 * jnp.cos(X[4]) + X[1] ** 2 - 2.0
    baseline = jnp.float32(float(jnp.var(y)))

    init_fn = _make_init_fn(options, n_feat, False)
    scalars = options.traced_scalars()
    states = init_fn(
        jax.random.split(jax.random.PRNGKey(0), options.npopulations),
        X, y, baseline, scalars,
    )
    it_fn = _make_iteration_fn(options, False)
    cm = jnp.int32(options.maxsize)

    def run():
        s2, ghof = it_fn(
            states, jax.random.PRNGKey(1), cm, X, y, baseline, scalars
        )
        jax.block_until_ready(ghof.losses)

    dt = _median_time(run, reps=3)
    cand_evals = (
        options.ncycles_per_iteration
        * options.n_parallel_tournaments
        * options.npopulations
    )
    return [
        {
            "suite": "search_iteration",
            "case": (
                f"islands{options.npopulations}_npop{options.npop}_"
                f"cycles{options.ncycles_per_iteration}_rows{n_rows}"
            ),
            "median_s": dt,
            "candidate_evals_per_s": cand_evals / dt,
        }
    ]


def bench_search_iteration_northstar():
    """BASELINE.json's north-star search shape (npopulations=64,
    npop=1000): at this scale the in-loop scoring batches clear
    _PALLAS_MIN_BATCH, so on TPU the evolution cycles themselves run
    through the Pallas eval kernel and constant optimization through the
    fused loss/grad kernels (optimizer_backend='auto'). Heavy — runs on
    non-CPU platforms or with SRTPU_SUITE_BIG=1."""
    import jax

    if jax.devices()[0].platform == "cpu" and not os.environ.get(
        "SRTPU_SUITE_BIG"
    ):
        return []
    import jax.numpy as jnp
    import numpy as np

    from symbolicregression_jl_tpu.api import _make_init_fn, _make_iteration_fn
    from symbolicregression_jl_tpu.models.options import make_options

    options = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        npop=1000,
        npopulations=64,
        ncycles_per_iteration=25,
        maxsize=20,
    )
    n_feat, n_rows = 1, 1000
    rng = np.random.default_rng(0)
    theta = rng.uniform(1.0, 3.0, n_rows).astype(np.float32)
    X = jnp.asarray(theta[None, :])
    y = jnp.asarray(
        (np.exp(-(theta**2) / 2.0) / np.sqrt(2 * np.pi)).astype(np.float32)
    )
    baseline = jnp.float32(float(jnp.var(y)))

    init_fn = _make_init_fn(options, n_feat, False)
    scalars = options.traced_scalars()
    states = init_fn(
        jax.random.split(jax.random.PRNGKey(0), options.npopulations),
        X, y, baseline, scalars,
    )
    it_fn = _make_iteration_fn(options, False)
    cm = jnp.int32(options.maxsize)

    def run():
        s2, ghof = it_fn(
            states, jax.random.PRNGKey(1), cm, X, y, baseline, scalars
        )
        jax.block_until_ready(ghof.losses)

    dt = _median_time(run, reps=3)
    cand_evals = (
        options.ncycles_per_iteration
        * options.n_parallel_tournaments
        * options.npopulations
    )
    out = [
        {
            "suite": "search_iteration_northstar",
            "case": (
                f"islands{options.npopulations}_npop{options.npop}_"
                f"cycles{options.ncycles_per_iteration}_rows{n_rows}"
            ),
            "median_s": dt,
            "candidate_evals_per_s": cand_evals / dt,
        }
    ]

    # breakdown (VERDICT r2 #2): where does the iteration go — evolve
    # cycles vs constant optimization? Re-time with the optimizer off
    # (one extra compile); the BFGS share is the difference. Host share
    # is negligible by construction (the whole iteration is ONE jit
    # call; host work happens between calls and is excluded by timing
    # block_until_ready around the call itself).
    try:
        opt_off = make_options(
            binary_operators=["+", "-", "*", "/"],
            unary_operators=["cos", "exp"],
            npop=1000,
            npopulations=64,
            ncycles_per_iteration=25,
            maxsize=20,
            should_optimize_constants=False,
        )
        it2 = _make_iteration_fn(opt_off, False)
        sc2 = opt_off.traced_scalars()

        def run2():
            s2, ghof = it2(
                states, jax.random.PRNGKey(1), cm, X, y, baseline, sc2
            )
            jax.block_until_ready(ghof.losses)

        dt2 = _median_time(run2, reps=3)
        out.append(
            {
                "suite": "search_iteration_northstar",
                "case": "breakdown",
                "full_s": dt,
                "no_optimizer_s": dt2,
                "bfgs_share": max(0.0, 1.0 - dt2 / dt),
            }
        )
    except Exception as e:  # pragma: no cover
        print(f"# northstar breakdown failed: {e}", file=sys.stderr)
    return out


def bench_precision_ratio():
    """float64 vs float32 population-scoring throughput on one workload.

    The reference's default dtype is Float64 with native-speed fused eval
    (reference src/InterfaceDynamicExpressions.jl:50-52); here f64 routes
    to the lockstep jnp interpreter (the Pallas kernel is f32/bf16-only —
    no native f64 on v5e), so this entry publishes the measured cost of
    choosing precision='float64'. Runs LAST: jax_enable_x64 is
    process-global."""
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from symbolicregression_jl_tpu.models.fitness import score_trees
    from symbolicregression_jl_tpu.models.mutate_device import (
        gen_random_tree_fixed_size,
    )
    from symbolicregression_jl_tpu.models.options import make_options

    n_trees, n_rows = 2048, 1000
    options = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        maxsize=20,
    )
    sizes = jax.random.randint(jax.random.PRNGKey(1), (n_trees,), 3, 20)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(
            k, s, 2, options.operators, options.max_len
        )
    )(jax.random.split(jax.random.PRNGKey(0), n_trees), sizes)
    rng = np.random.default_rng(0)
    X_h = rng.standard_normal((2, n_rows))
    y_h = 2.0 * np.cos(X_h[1]) + X_h[0] ** 2

    out = []
    rates = {}
    for name, ftype in (("float32", np.float32), ("float64", np.float64)):
        X = jnp.asarray(X_h.astype(ftype))
        y = jnp.asarray(y_h.astype(ftype))
        t = trees._replace(cval=trees.cval.astype(X.dtype))
        bl = jnp.asarray(np.var(y_h).astype(ftype))
        f = jax.jit(
            lambda t, X, y, bl: score_trees(t, X, y, None, bl, options)
        )
        f(t, X, y, bl)
        dt = _median_time(
            lambda: jax.block_until_ready(f(t, X, y, bl)), reps=3
        )
        rates[name] = n_trees * n_rows / dt
        out.append(
            {
                "suite": "precision_ratio",
                "case": name,
                "median_s": dt,
                "trees_rows_per_s": rates[name],
            }
        )
    out.append(
        {
            "suite": "precision_ratio",
            "case": "f32_over_f64",
            "ratio": rates["float32"] / rates["float64"],
        }
    )
    return out


def main():
    from bench import _devices_or_cpu_fallback

    devices = _devices_or_cpu_fallback(verbose=True, use_memo=True)  # hung-tunnel watchdog
    platform = devices[0].platform
    results = []
    for fn in (
        bench_eval_fixed_tree,
        bench_single_eval_48_nodes,
        bench_population_scoring,
        bench_search_iteration,
        bench_search_iteration_northstar,
        bench_precision_ratio,  # keep last: flips jax_enable_x64
    ):
        try:
            results.extend(fn())
        except Exception as e:  # pragma: no cover
            # stderr for the human; a JSON error entry for the record —
            # a partially-failed suite must be visibly partial in the
            # watcher's captured artifact, not silently missing entries
            print(f"# {fn.__name__} failed: {e}", file=sys.stderr)
            results.append(
                {
                    "suite": fn.__name__.removeprefix("bench_"),
                    "error": f"{type(e).__name__}: {str(e)[:200]}",
                }
            )
    for r in results:
        r["platform"] = platform
        print(json.dumps(r))


if __name__ == "__main__":
    main()
