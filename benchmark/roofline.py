"""Roofline model for the Pallas tree-interpreter kernel.

The kernel (ops/pallas_eval.py) evaluates every candidate operator per
slot and muxes the result — so its compute cost per (tree, slot, row) is
the SUM of the operator-set's vector-op costs plus the select tree, and
the relevant peak is the VPU vector-issue rate (the MXU plays no part: a
tree interpreter has no matmuls).

    bound_trees_rows_per_s = VPU_rate / (ops_per_slot * avg_slots)

Two alternative bounds are computed and the binding one reported:

* VPU issue: ops_per_slot x avg executed slots per tree (dynamic slot
  loop runs ceil(len/4)*4 slots on length-sorted trees).
* VMEM scratch traffic: each slot reads 2 and writes 1 (r_sub, 128) value
  tile -> 12 B/row/slot in f32 (6 B in bf16 — the bf16 variant halves
  this term but NOT the issue term, which is why bf16 only pays when the
  kernel is VMEM-bound).

Peak numbers are parameters with conservative public defaults for TPU
v5e (VPU: 8 sublanes x 128 lanes x 4 SIMD subunits x ~0.94 GHz ~= 3.9e12
f32 op/s; VMEM bandwidth taken as ~2e13 B/s); override with measured
values when available. The per-op cost table is a coarse static model
(transcendentals ~8 slots of the vector pipeline, div ~4, arithmetic 1);
treat the bound as a scale anchor, not a promise.
"""

from __future__ import annotations

from typing import Dict

# vector-op cost of one candidate evaluation, in VPU issue slots
_OP_COST = {
    "+": 1.0, "-": 1.0, "*": 1.0, "neg": 1.0, "abs": 1.0,
    "square": 1.0, "cube": 2.0, "relu": 1.0, "greater": 1.0,
    "logical_or": 2.0, "logical_and": 2.0, "min": 1.0, "max": 1.0,
    "/": 4.0, "pow": 12.0, "mod": 6.0,
    "cos": 8.0, "sin": 8.0, "tan": 10.0, "exp": 8.0, "log": 8.0,
    "log2": 8.0, "log10": 8.0, "log1p": 9.0, "sqrt": 4.0, "cbrt": 8.0,
    "acos": 10.0, "asin": 10.0, "atan": 10.0, "sinh": 10.0,
    "cosh": 10.0, "tanh": 9.0, "acosh": 12.0, "asinh": 12.0,
    "atanh": 12.0, "erf": 10.0, "erfc": 10.0, "gamma": 16.0,
    "lgamma": 16.0, "sign": 1.0, "exp2": 8.0,
}
_DEFAULT_COST = 6.0  # unknown / custom ops

V5E_VPU_OPS = 3.9e12  # f32 vector op/s (8x128 lanes x 4 subunits x .94GHz)
V5E_VMEM_BW = 2.0e13  # B/s, order-of-magnitude scratch bandwidth


def _safe_overhead(name: str) -> float:
    """NaN-guarding (domain masks + where) adds ~2 selects for the ops
    that need it."""
    return 2.0 if name in (
        "/", "log", "log2", "log10", "log1p", "sqrt", "acosh", "atanh",
        "pow", "gamma",
    ) else 0.0


def ops_per_slot(operators, program: str = "postfix") -> float:
    """Vector ops issued per (tree, step, row).

    program="postfix": every candidate computed per slot + the log2-deep
    select mux + leaf broadcast/compare overhead. program="instr"
    (compressed operator-only program): same candidate set per step, but
    each step additionally pays the 2-operand source mux (2 loads + 2
    selects + broadcast each) and the operand-finiteness poison check —
    in exchange for executing ~half as many steps (use the instruction
    count, not the postfix length, for avg_tree_len)."""
    import math

    names = list(operators.unary_names) + list(operators.binary_names)
    compute = sum(
        _OP_COST.get(n, _DEFAULT_COST) + _safe_overhead(n) for n in names
    )
    n_codes = (3 if program == "postfix" else 2) + len(names)
    mux = math.ceil(math.log2(max(n_codes, 2)))  # balanced select tree
    if program in ("instr", "instr_packed"):
        # instr: 2 operands x (2 dynamic loads + 2 selects + bcast);
        # instr_packed's unified operand scratch drops one dynamic load
        # per operand — its bigger win (one packed SMEM word per step) is
        # scalar-unit relief the vector-issue bound can't see
        fetch = 10.0 if program == "instr" else 6.0
        poison = 4.0  # isfinite(v,a,b) + and + max accumulate
        return compute + mux + fetch + poison
    leaf = 2.0  # const broadcast + var pick
    poison = 2.0  # isfinite + max accumulate
    return compute + mux + leaf + poison


def kernel_roofline(
    operators,
    avg_tree_len: float,
    compute_dtype: str = "float32",
    vpu_ops: float = V5E_VPU_OPS,
    vmem_bw: float = V5E_VMEM_BW,
    program: str = "postfix",
) -> Dict[str, float]:
    """Upper bounds on kernel throughput in trees*rows/s.

    avg_tree_len: mean EXECUTED steps per tree — with the dynamic slot
    loop and length sorting that is mean(ceil(len/4)*4) over the batch,
    where len is the postfix length (program="postfix") or the
    instruction count (program="instr").
    """
    per_slot = ops_per_slot(operators, program)
    issue_bound = vpu_ops / (per_slot * avg_tree_len)
    bytes_per = 4 if compute_dtype == "float32" else 2
    # per step per row — postfix: 2 scratch reads + 1 write. instr: both
    # dynamic loads per operand materialize (scratch + X) -> 4 reads +
    # 1 write. instr_packed: 1 unified-scratch read per operand -> 2 + 1.
    accesses = {"postfix": 3, "instr": 5, "instr_packed": 3}[program]
    vmem_bound = vmem_bw / (accesses * bytes_per * avg_tree_len)
    return {
        "ops_per_slot": per_slot,
        "avg_slots": avg_tree_len,
        "issue_bound": issue_bound,
        "vmem_bound": vmem_bound,
        "bound": min(issue_bound, vmem_bound),
        "binding": "issue" if issue_bound < vmem_bound else "vmem",
    }


def report(operators, avg_tree_len: float, measured_rate: float,
           compute_dtype: str = "float32", program: str = "postfix") -> str:
    r = kernel_roofline(operators, avg_tree_len, compute_dtype,
                        program=program)
    frac = measured_rate / r["bound"] if r["bound"] > 0 else float("nan")
    return (
        f"roofline[{program},{compute_dtype}]: "
        f"{r['ops_per_slot']:.0f} vec-ops/slot x "
        f"{r['avg_slots']:.1f} slots -> issue bound "
        f"{r['issue_bound']:.2e} t-r/s, vmem bound {r['vmem_bound']:.2e} "
        f"(binding: {r['binding']}); measured {measured_rate:.2e} = "
        f"{100 * frac:.0f}% of bound"
    )


def fit_slot_model(points):
    """Decompose measured per-step cost into per-step overhead + per-
    vector-op compute by linear least squares.

    points: [(vec_ops_per_slot, seconds_per_iteration), ...] measured on
    ONE workload whose programs are held fixed while only the candidate
    set widens (benchmark/opset_sweep.py: trees built over {+,*},
    evaluated under growing operator sets — the step stream is
    identical, so any time difference is candidate compute).

    Returns {"overhead_frac": fraction of the richest point's step cost
    NOT attributable to candidate compute, "per_op_s", "fixed_s",
    "effective_bound_scale": how much of the naive issue bound the fixed
    per-step cost forfeits at the richest point}. Fractions are clamped
    to [0, 1]; measurement noise can drive the raw intercept slightly
    negative (the unclamped values are in fixed_s/per_op_s).

    Caveat: widening the candidate set also deepens the balanced select
    mux by log2(n_cands) and reshapes the select tree, so the fitted
    slope conflates mux-depth cost with candidate compute and part of
    the mux lands in the intercept. The two-term fit is a sound BOUND on
    recoverable compute (the mux is as unavoidable as the candidates in
    this kernel design) but should not be read as a pure
    overhead-vs-compute split.
    """
    import numpy as np

    if len(points) < 2:
        raise ValueError(
            f"fit_slot_model needs >= 2 (vec_ops, time) points to "
            f"separate overhead from compute, got {len(points)}"
        )
    xs = np.asarray([p[0] for p in points], dtype=np.float64)
    ys = np.asarray([p[1] for p in points], dtype=np.float64)
    if np.ptp(xs) <= 0:
        raise ValueError(
            "fit_slot_model needs points at distinct vec_ops values; "
            f"all {len(points)} share vec_ops={xs[0]:g}"
        )
    A = np.stack([np.ones_like(xs), xs], axis=1)
    (fixed_s, per_op_s), *_ = np.linalg.lstsq(A, ys, rcond=None)
    x_rich = float(xs.max())
    compute_s = per_op_s * x_rich
    total_s = fixed_s + compute_s
    frac = float(fixed_s / total_s) if total_s > 0 else 0.0
    frac = min(max(frac, 0.0), 1.0)
    return {
        "fixed_s": float(fixed_s),
        "per_op_s": float(per_op_s),
        "overhead_frac": frac,
        "effective_bound_scale": 1.0 - frac,
    }
