#!/usr/bin/env python
"""Throughput A/B of the constant-optimization objective on real TPU:
the fused Pallas loss+grad kernel (ops/pallas_grad.py) vs `jax.grad`
through the vmapped lockstep interpreter (the models/constant_opt.py
default path) on the bench.py workload shape.

Prints trees-rows/s for (a) loss+grad batch, (b) loss-only batch (the
line-search evaluator), for both backends. Usage:
    python benchmark/grad_bench.py [n_trees] [n_inner]
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    from bench import (
        N_ROWS,
        _build_workload,
        _devices_or_cpu_fallback,
        _dispatch_overhead_s,
        _feynman_data,
    )

    _devices_or_cpu_fallback(verbose=True, use_memo=True)
    from symbolicregression_jl_tpu.models.options import make_options
    from symbolicregression_jl_tpu.ops.interpreter import eval_trees
    from symbolicregression_jl_tpu.ops.losses import aggregate_loss
    from symbolicregression_jl_tpu.ops.pallas_grad import make_loss_kernel

    args = sys.argv[1:]
    n_trees = int(args[0]) if args else 4096
    n_inner = int(args[1]) if len(args) > 1 else 10

    options = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        maxsize=20,
    )
    ops = options.operators
    dev = jax.devices()[0]
    print(f"# device: {dev} ({dev.platform})", file=sys.stderr)

    trees = _build_workload(jax, jnp, options, n_trees, 1)
    X_h, y_h = _feynman_data()
    X = jnp.asarray(X_h)
    y = jnp.asarray(y_h)
    overhead = _dispatch_overhead_s(jax, jnp, dev)

    def timeit(fn):
        t0 = time.perf_counter()
        fn()
        compile_s = time.perf_counter() - t0
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        per = max((float(np.median(ts)) - overhead) / n_inner, 1e-9)
        return n_trees * N_ROWS / per, per, compile_s

    results = []

    # fused kernels: structure staged once, constants swapped per call
    for with_grad, label in ((True, "fused loss+grad"),
                             (False, "fused loss-only")):
        fn = make_loss_kernel(
            trees, X, y, None, ops, with_grad=with_grad
        )

        def run(fn=fn):
            def body(i, acc):
                out = fn(trees.cval + acc * 1e-12)
                loss = out[0]
                return acc + jnp.clip(
                    jnp.mean(jnp.where(jnp.isfinite(loss), loss, 0.0)),
                    0.0, 1.0,
                )

            return float(jax.jit(
                lambda: jax.lax.fori_loop(0, n_inner, body, jnp.float32(0.0))
            )())

        try:
            rate, per, comp = timeit(run)
        except Exception as e:
            print(f"FAIL {label}: {type(e).__name__}: {e}", file=sys.stderr)
            continue
        results.append((label, rate))
        print(f"{rate:.3e} t-r/s  {per*1e3:7.2f} ms/iter  "
              f"(compile {comp:.0f}s)  {label}", flush=True)

    # interpreter autodiff baseline (the vmapped per-member closure path)
    def member_loss(cval, kind, op, feat, length):
        from symbolicregression_jl_tpu.models.trees import TreeBatch
        t = TreeBatch(kind=kind[None], op=op[None], feat=feat[None],
                      cval=cval[None], length=length[None])
        yp, ok = eval_trees(t, X, ops)
        elem = (yp[0] - y) ** 2
        loss = aggregate_loss(elem, None)
        return jnp.where(ok[0] & jnp.isfinite(loss), loss, jnp.inf)

    vg = jax.vmap(jax.value_and_grad(member_loss),
                  in_axes=(0, 0, 0, 0, 0))

    def run_autodiff():
        def body(i, acc):
            f, g = vg(trees.cval + acc * 1e-12, trees.kind, trees.op,
                      trees.feat, trees.length)
            return acc + jnp.clip(
                jnp.mean(jnp.where(jnp.isfinite(f), f, 0.0)), 0.0, 1.0
            )

        return float(jax.jit(
            lambda: jax.lax.fori_loop(0, n_inner, body, jnp.float32(0.0))
        )())

    try:
        rate, per, comp = timeit(run_autodiff)
        results.append(("interpreter value_and_grad (vmap)", rate))
        print(f"{rate:.3e} t-r/s  {per*1e3:7.2f} ms/iter  "
              f"(compile {comp:.0f}s)  interpreter value_and_grad (vmap)",
              flush=True)
    except Exception as e:
        print(f"FAIL autodiff baseline: {type(e).__name__}: {e}",
              file=sys.stderr)

    if results:
        best = max(results, key=lambda r: r[1])
        print(f"\nBEST: {best[1]:.3e} trees-rows/s  {best[0]}")


if __name__ == "__main__":
    main()
