// srtpu_native — C++ host runtime for symbolicregression_jl_tpu.
//
// The TPU compute path (fitness evaluation, evolution, BFGS) is JAX/XLA/
// Pallas; this library is the *host* runtime around it — the pointer-chasing
// work the reference keeps in Julia/DynamicExpressions (linked Node{T}
// trees, `string_tree`, `simplify_tree`/`combine_operators`, dataset IO):
//
//   * infix expression parser      (analog of parsing in the reference's
//                                   SymbolicUtils round-trip)
//   * batched postfix -> infix     (string_tree, reference
//     printer                       src/InterfaceDynamicExpressions.jl:132-153;
//                                   hot for the recorder, which stringifies
//                                   whole populations every iteration)
//   * simplifier: constant folding (simplify_tree + combine_operators,
//     + operator-chain combining    applied at src/SingleIteration.jl:73-74)
//   * multithreaded batched postfix (the reference's CPU eval path:
//     evaluator                     DynamicExpressions eval_tree_array —
//                                   used as preflight oracle + CPU anchor)
//   * CSV dataset loader           (host IO off the Python interpreter)
//
// Expression encoding matches models/trees.py exactly: flat postfix slots
// (kind, op, feat, cval) + length, kind in {PAD=0, CONST=1, VAR=2, UNA=3,
// BIN=4}. Operator *semantics* (NaN-safe domains) match ops/operators.py —
// the Python wrapper maps each OperatorSet name to a native opcode via
// srt_op_id() and refuses to route custom (Python-registered) operators
// here.
//
// Pure C ABI (ctypes-friendly): no exceptions across the boundary, caller
// owns all buffers.

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr int KPAD = 0, KCONST = 1, KVAR = 2, KUNA = 3, KBIN = 4;

// ---------------------------------------------------------------------------
// Operator table (semantics mirror ops/operators.py NaN-safe definitions)
// ---------------------------------------------------------------------------

enum UnaOp : int32_t {
  U_COS, U_SIN, U_TAN, U_EXP, U_LOG, U_LOG2, U_LOG10, U_LOG1P, U_SQRT,
  U_ABS, U_SQUARE, U_CUBE, U_NEG, U_RELU, U_SINH, U_COSH, U_TANH,
  U_ASIN, U_ACOS, U_ATAN, U_ASINH, U_ACOSH, U_ATANH_CLIP, U_ERF, U_ERFC,
  U_GAMMA, U_SIGMOID, U_GAUSS, U_INV, U_SIGN, U_IDENTITY,
  U_COUNT
};

enum BinOp : int32_t {
  B_ADD, B_SUB, B_MUL, B_DIV, B_POW, B_MOD, B_MAX, B_MIN, B_GREATER,
  B_LOGICAL_OR, B_LOGICAL_AND, B_ATAN2,
  B_COUNT
};

const char* kUnaNames[U_COUNT] = {
  "cos", "sin", "tan", "exp", "log", "log2", "log10", "log1p", "sqrt",
  "abs", "square", "cube", "neg", "relu", "sinh", "cosh", "tanh",
  "asin", "acos", "atan", "asinh", "acosh", "atanh", "erf", "erfc",
  "gamma", "sigmoid", "gauss", "inv", "sign", "identity",
};

const char* kBinNames[B_COUNT] = {
  "+", "-", "*", "/", "^", "mod", "max", "min", "greater",
  "logical_or", "logical_and", "atan2",
};

const double kNaN = std::nan("");

inline double apply_una(int32_t o, double a) {
  switch (o) {
    case U_COS: return std::cos(a);
    case U_SIN: return std::sin(a);
    case U_TAN: return std::tan(a);
    case U_EXP: return std::exp(a);
    case U_LOG: return a > 0 ? std::log(a) : kNaN;
    case U_LOG2: return a > 0 ? std::log2(a) : kNaN;
    case U_LOG10: return a > 0 ? std::log10(a) : kNaN;
    case U_LOG1P: return a > -1 ? std::log1p(a) : kNaN;
    case U_SQRT: return a >= 0 ? std::sqrt(a) : kNaN;
    case U_ABS: return std::fabs(a);
    case U_SQUARE: return a * a;
    case U_CUBE: return a * a * a;
    case U_NEG: return -a;
    case U_RELU: return a > 0 ? a : 0.0;
    case U_SINH: return std::sinh(a);
    case U_COSH: return std::cosh(a);
    case U_TANH: return std::tanh(a);
    case U_ASIN: return std::fabs(a) <= 1 ? std::asin(a) : kNaN;
    case U_ACOS: return std::fabs(a) <= 1 ? std::acos(a) : kNaN;
    case U_ATAN: return std::atan(a);
    case U_ASINH: return std::asinh(a);
    case U_ACOSH: return a >= 1 ? std::acosh(a) : kNaN;
    case U_ATANH_CLIP: {
      // atanh of x wrapped into (-1,1): jnp.mod semantics (result sign of
      // divisor, i.e. non-negative for divisor 2).
      double m = std::fmod(a + 1.0, 2.0);
      if (m < 0) m += 2.0;
      return std::atanh(m - 1.0);
    }
    case U_ERF: return std::erf(a);
    case U_ERFC: return std::erfc(a);
    case U_GAMMA: {
      double g = std::tgamma(a);
      bool pole = a <= 0 && a == std::round(a);
      return (pole || !std::isfinite(g)) ? kNaN : g;
    }
    case U_SIGMOID: return 1.0 / (1.0 + std::exp(-a));
    case U_GAUSS: return std::exp(-(a * a));
    case U_INV: return 1.0 / a;
    case U_SIGN: return (a > 0) - (a < 0);
    case U_IDENTITY: return a;
    default: return kNaN;
  }
}

inline double apply_bin(int32_t o, double a, double b) {
  switch (o) {
    case B_ADD: return a + b;
    case B_SUB: return a - b;
    case B_MUL: return a * b;
    case B_DIV: return a / b;
    case B_POW: {
      // safe_pow (ops/operators.py:38-47 / reference src/Operators.jl:38-46)
      bool bad = (a < 0 && b != std::round(b)) || (a == 0 && b < 0);
      return bad ? kNaN : std::pow(a, b);
    }
    case B_MOD: {
      double m = std::fmod(a, b);
      if (m != 0 && ((m < 0) != (b < 0))) m += b;  // jnp.mod semantics
      return m;
    }
    case B_MAX: return std::fmax(a, b);
    case B_MIN: return std::fmin(a, b);
    case B_GREATER: return a > b ? 1.0 : 0.0;
    case B_LOGICAL_OR: return (a > 0 || b > 0) ? 1.0 : 0.0;
    case B_LOGICAL_AND: return (a > 0 && b > 0) ? 1.0 : 0.0;
    case B_ATAN2: return std::atan2(a, b);
    default: return kNaN;
  }
}

// ---------------------------------------------------------------------------
// Helpers
// ---------------------------------------------------------------------------

std::vector<std::string> split_lines(const char* joined) {
  std::vector<std::string> out;
  if (!joined || !*joined) return out;
  const char* p = joined;
  while (*p) {
    const char* q = std::strchr(p, '\n');
    if (!q) { out.emplace_back(p); break; }
    out.emplace_back(p, q - p);
    p = q + 1;
  }
  return out;
}

void set_err(char* err, int cap, const std::string& msg) {
  if (err && cap > 0) {
    std::snprintf(err, static_cast<size_t>(cap), "%s", msg.c_str());
  }
}

struct Node { int32_t kind, op, feat; double cval; int32_t l, r; };

// postfix slots -> node array with child links; returns root index or -1
int build_nodes(const int32_t* kind, const int32_t* op, const int32_t* feat,
                const float* cval, int32_t n, std::vector<Node>& nodes) {
  nodes.clear();
  nodes.reserve(n);
  std::vector<int32_t> stack;
  for (int32_t i = 0; i < n; ++i) {
    Node nd{kind[i], op[i], feat[i], static_cast<double>(cval[i]), -1, -1};
    if (nd.kind == KUNA) {
      if (stack.empty()) return -1;
      nd.l = stack.back(); stack.pop_back();
    } else if (nd.kind == KBIN) {
      if (stack.size() < 2) return -1;
      nd.r = stack.back(); stack.pop_back();
      nd.l = stack.back(); stack.pop_back();
    } else if (nd.kind != KCONST && nd.kind != KVAR) {
      return -1;  // PAD inside valid region
    }
    nodes.push_back(nd);
    stack.push_back(i);
  }
  if (stack.size() != 1) return -1;
  return stack[0];
}

// re-emit postfix from node graph; returns length or -1 if it exceeds L
int32_t emit_postfix(const std::vector<Node>& nodes, int root, int32_t L,
                     int32_t* kind, int32_t* op, int32_t* feat, float* cval) {
  std::vector<int32_t> order;
  order.reserve(nodes.size());
  // iterative postorder
  std::vector<std::pair<int32_t, bool>> st;
  st.push_back({static_cast<int32_t>(root), false});
  while (!st.empty()) {
    auto [idx, visited] = st.back();
    st.pop_back();
    if (visited) { order.push_back(idx); continue; }
    st.push_back({idx, true});
    const Node& nd = nodes[idx];
    if (nd.r >= 0) st.push_back({nd.r, false});
    if (nd.l >= 0) st.push_back({nd.l, false});
  }
  if (static_cast<int32_t>(order.size()) > L) return -1;
  for (size_t i = 0; i < order.size(); ++i) {
    const Node& nd = nodes[order[i]];
    kind[i] = nd.kind;
    op[i] = nd.kind == KUNA || nd.kind == KBIN ? nd.op : 0;
    feat[i] = nd.kind == KVAR ? nd.feat : 0;
    cval[i] = nd.kind == KCONST ? static_cast<float>(nd.cval) : 0.0f;
  }
  for (int32_t i = static_cast<int32_t>(order.size()); i < L; ++i) {
    kind[i] = KPAD; op[i] = 0; feat[i] = 0; cval[i] = 0.0f;
  }
  return static_cast<int32_t>(order.size());
}

}  // namespace

extern "C" {

// Bump on EVERY exported-signature change: the Python wrapper refuses to
// load a library whose version it wasn't built against (a stale .so with
// the old srt_eval_batch signature would silently return garbage losses).
int32_t srt_abi_version() { return 2; }

// name -> native opcode (or -1). is_binary selects the table.
int32_t srt_op_id(const char* name, int32_t is_binary) {
  if (is_binary) {
    for (int32_t i = 0; i < B_COUNT; ++i)
      if (!std::strcmp(name, kBinNames[i])) return i;
  } else {
    for (int32_t i = 0; i < U_COUNT; ++i)
      if (!std::strcmp(name, kUnaNames[i])) return i;
  }
  return -1;
}

// ---------------------------------------------------------------------------
// Batched printer (analog of string_tree). Strings are written NUL-terminated
// back-to-back into `out`; offsets[t] = byte offset of tree t. Returns total
// bytes used, or -(needed) if out_cap is too small (caller retries), or 0 on
// malformed input.
// ---------------------------------------------------------------------------

int64_t srt_print_batch(int64_t T, int32_t L,
                        const int32_t* kind, const int32_t* op,
                        const int32_t* feat, const float* cval,
                        const int32_t* length,
                        const char* una_names_joined,
                        const char* bin_names_joined,
                        const char* var_names_joined,
                        const uint8_t* bin_infix,
                        char* out, int64_t out_cap, int64_t* offsets) {
  auto unames = split_lines(una_names_joined);
  auto bnames = split_lines(bin_names_joined);
  auto vnames = split_lines(var_names_joined);
  std::string buf;
  buf.reserve(static_cast<size_t>(T) * 32);
  char tmp[64];
  for (int64_t t = 0; t < T; ++t) {
    offsets[t] = static_cast<int64_t>(buf.size());
    const int32_t* k = kind + t * L;
    const int32_t* o = op + t * L;
    const int32_t* f = feat + t * L;
    const float* c = cval + t * L;
    int32_t n = length[t];
    if (n <= 0 || n > L) { buf += '\0'; continue; }
    // stack of rendered sub-strings
    std::vector<std::string> st;
    bool ok = true;
    for (int32_t i = 0; i < n && ok; ++i) {
      switch (k[i]) {
        case KCONST:
          // %.6g matches models/trees.py _format_const
          std::snprintf(tmp, sizeof tmp, "%.6g",
                        static_cast<double>(c[i]));
          st.emplace_back(tmp);
          break;
        case KVAR:
          if (f[i] >= 0 && f[i] < static_cast<int32_t>(vnames.size())) {
            st.push_back(vnames[f[i]]);
          } else {
            std::snprintf(tmp, sizeof tmp, "x%d", f[i]);
            st.emplace_back(tmp);
          }
          break;
        case KUNA: {
          if (st.empty() ||
              o[i] >= static_cast<int32_t>(unames.size())) { ok = false; break; }
          std::string a = std::move(st.back()); st.pop_back();
          st.push_back(unames[o[i]] + "(" + a + ")");
          break;
        }
        case KBIN: {
          if (st.size() < 2 ||
              o[i] >= static_cast<int32_t>(bnames.size())) { ok = false; break; }
          std::string b = std::move(st.back()); st.pop_back();
          std::string a = std::move(st.back()); st.pop_back();
          const std::string& nm = bnames[o[i]];
          if (bin_infix[o[i]]) {
            st.push_back("(" + a + " " + nm + " " + b + ")");
          } else {
            st.push_back(nm + "(" + a + ", " + b + ")");
          }
          break;
        }
        default:
          ok = false;
      }
    }
    if (ok && st.size() == 1) buf += st[0];
    buf += '\0';
  }
  int64_t needed = static_cast<int64_t>(buf.size());
  if (needed > out_cap) return -needed;
  std::memcpy(out, buf.data(), static_cast<size_t>(needed));
  return needed;
}

// ---------------------------------------------------------------------------
// Infix parser (grammar of models/trees.py parse_expression): + - * / ^ with
// precedence, right-assoc ^, unary minus, f(x), f(x, y), floats, variables
// (names list or x<k>). Returns postfix length, or -1 with err filled.
// ---------------------------------------------------------------------------

namespace {

struct Parser {
  std::vector<std::string> toks;
  size_t pos = 0;
  const std::vector<std::string>& unames;
  const std::vector<std::string>& bnames;
  const std::vector<std::string>& vnames;
  std::vector<Node>& nodes;
  std::string err;

  Parser(const std::string& s, const std::vector<std::string>& u,
         const std::vector<std::string>& b, const std::vector<std::string>& v,
         std::vector<Node>& nd)
      : unames(u), bnames(b), vnames(v), nodes(nd) {
    size_t i = 0;
    while (i < s.size()) {
      char ch = s[i];
      if (std::isspace(static_cast<unsigned char>(ch))) { ++i; continue; }
      if (std::isalpha(static_cast<unsigned char>(ch)) || ch == '_') {
        size_t j = i;
        while (j < s.size() &&
               (std::isalnum(static_cast<unsigned char>(s[j])) || s[j] == '_'))
          ++j;
        toks.push_back(s.substr(i, j - i));
        i = j;
      } else if (std::isdigit(static_cast<unsigned char>(ch)) || ch == '.') {
        size_t j = i;
        while (j < s.size() &&
               (std::isdigit(static_cast<unsigned char>(s[j])) || s[j] == '.'))
          ++j;
        if (j < s.size() && (s[j] == 'e' || s[j] == 'E')) {
          size_t j2 = j + 1;
          if (j2 < s.size() && (s[j2] == '+' || s[j2] == '-')) ++j2;
          if (j2 < s.size() && std::isdigit(static_cast<unsigned char>(s[j2]))) {
            while (j2 < s.size() &&
                   std::isdigit(static_cast<unsigned char>(s[j2])))
              ++j2;
            j = j2;
          }
        }
        toks.push_back(s.substr(i, j - i));
        i = j;
      } else {
        toks.push_back(std::string(1, ch));
        ++i;
      }
    }
  }

  const std::string* peek() const { return pos < toks.size() ? &toks[pos] : nullptr; }
  std::string take() { return toks[pos++]; }
  bool fail(const std::string& m) { if (err.empty()) err = m; return false; }

  int32_t add(Node nd) {
    nodes.push_back(nd);
    return static_cast<int32_t>(nodes.size() - 1);
  }

  int find(const std::vector<std::string>& v, const std::string& s) const {
    for (size_t i = 0; i < v.size(); ++i)
      if (v[i] == s) return static_cast<int>(i);
    return -1;
  }

  bool is_number(const std::string& t) const {
    return !t.empty() &&
           (std::isdigit(static_cast<unsigned char>(t[0])) || t[0] == '.');
  }

  bool expect(const char* tok) {
    if (pos >= toks.size() || toks[pos] != tok)
      return fail(std::string("expected '") + tok + "'");
    ++pos;
    return true;
  }

  bool primary(int32_t* out) {
    if (pos >= toks.size()) return fail("unexpected end of expression");
    std::string t = take();
    if (t == "(") {
      if (!sum(out)) return false;
      return expect(")");
    }
    if (t == "-") {
      int32_t child;
      if (!primary(&child)) return false;
      if (nodes[child].kind == KCONST && nodes[child].l < 0) {
        nodes[child].cval = -nodes[child].cval;
        *out = child;
        return true;
      }
      int ni = find(unames, "neg");
      if (ni >= 0) {
        *out = add({KUNA, ni, 0, 0.0, child, -1});
        return true;
      }
      int bi = find(bnames, "-");
      if (bi < 0) return fail("no neg/'-' operator for unary minus");
      int32_t zero = add({KCONST, 0, 0, 0.0, -1, -1});
      *out = add({KBIN, bi, 0, 0.0, zero, child});
      return true;
    }
    if (is_number(t)) {
      char* end = nullptr;
      double v = std::strtod(t.c_str(), &end);
      if (!end || *end != '\0')  // e.g. '1.2.3' tokenizes as one number
        return fail("malformed number '" + t + "'");
      *out = add({KCONST, 0, 0, v, -1, -1});
      return true;
    }
    // identifier
    if (peek() && *peek() == "(") {
      take();
      std::vector<int32_t> args;
      int32_t a;
      if (!sum(&a)) return false;
      args.push_back(a);
      while (peek() && *peek() == ",") {
        take();
        if (!sum(&a)) return false;
        args.push_back(a);
      }
      if (!expect(")")) return false;
      if (args.size() == 1) {
        int ui = find(unames, t);
        if (ui < 0) return fail("unknown unary operator '" + t + "'");
        *out = add({KUNA, ui, 0, 0.0, args[0], -1});
        return true;
      }
      if (args.size() == 2) {
        int bi = find(bnames, t);
        if (bi < 0) return fail("unknown binary operator '" + t + "'");
        *out = add({KBIN, bi, 0, 0.0, args[0], args[1]});
        return true;
      }
      return fail("operators take 1 or 2 arguments");
    }
    int vi = find(vnames, t);
    if (vi < 0 && vnames.empty() && t.size() > 1 && t[0] == 'x') {
      bool digits = true;
      for (size_t i = 1; i < t.size(); ++i)
        digits = digits && std::isdigit(static_cast<unsigned char>(t[i]));
      if (digits) vi = std::atoi(t.c_str() + 1);
    }
    if (vi < 0) return fail("unknown identifier '" + t + "'");
    *out = add({KVAR, 0, vi, 0.0, -1, -1});
    return true;
  }

  bool power(int32_t* out) {
    if (!primary(out)) return false;
    if (peek() && *peek() == "^") {
      take();
      int32_t rhs;
      if (!power(&rhs)) return false;  // right-assoc
      int bi = find(bnames, "^");
      if (bi < 0) return fail("'^' not in operator set");
      *out = add({KBIN, bi, 0, 0.0, *out, rhs});
    }
    return true;
  }

  bool product(int32_t* out) {
    if (!power(out)) return false;
    while (peek() && (*peek() == "*" || *peek() == "/")) {
      std::string t = take();
      int32_t rhs;
      if (!power(&rhs)) return false;
      int bi = find(bnames, t);
      if (bi < 0) return fail("'" + t + "' not in operator set");
      *out = add({KBIN, bi, 0, 0.0, *out, rhs});
    }
    return true;
  }

  bool sum(int32_t* out) {
    if (!product(out)) return false;
    while (peek() && (*peek() == "+" || *peek() == "-")) {
      std::string t = take();
      int32_t rhs;
      if (!product(&rhs)) return false;
      int bi = find(bnames, t);
      if (bi < 0) return fail("'" + t + "' not in operator set");
      *out = add({KBIN, bi, 0, 0.0, *out, rhs});
    }
    return true;
  }
};

}  // namespace

int32_t srt_parse(const char* s,
                  const char* una_names_joined, const char* bin_names_joined,
                  const char* var_names_joined, int32_t L,
                  int32_t* kind, int32_t* op, int32_t* feat, float* cval,
                  char* err, int32_t err_cap) {
  auto unames = split_lines(una_names_joined);
  auto bnames = split_lines(bin_names_joined);
  auto vnames = split_lines(var_names_joined);
  std::vector<Node> nodes;
  Parser p(s ? s : "", unames, bnames, vnames, nodes);
  int32_t root;
  if (!p.sum(&root) || p.pos != p.toks.size()) {
    set_err(err, err_cap,
            p.err.empty() ? std::string("trailing tokens") : p.err);
    return -1;
  }
  int32_t n = emit_postfix(nodes, root, L, kind, op, feat, cval);
  if (n < 0) {
    set_err(err, err_cap, "expression exceeds max_len");
    return -1;
  }
  return n;
}

// ---------------------------------------------------------------------------
// Simplifier: combine_operators + constant folding to a fixed point.
// Semantics mirror models/mutate_device.py simplify_tree/_combine_pass:
//   fold:    any operator subtree whose value is a finite constant collapses
//   combine: (Lc1 in) out c2 rules over {+,-,*,/}; commutative rotation of
//            constant left children for + and *
// Arrays are modified in place. Returns number of trees changed, or -1.
// una_map/bin_map translate the tree's op indices to native opcodes.
// ---------------------------------------------------------------------------

namespace {

struct CombineTables {
  // [inner][outer] -> result op (or -1); fold value computed by rule id
  int fold_rule[4][4];  // indices: 0=+,1=-,2=*,3=/ within set or -1
  int set_idx[4];       // operator-set index of +,-,*,/ (or -1)
  int native_of_set(int set_op, const int32_t* bin_map, int n_bin) const {
    return set_op >= 0 && set_op < n_bin ? bin_map[set_op] : -1;
  }
};

// returns arithmetic family slot for a native binary opcode (or -1)
inline int fam(int32_t native) {
  switch (native) {
    case B_ADD: return 0;
    case B_SUB: return 1;
    case B_MUL: return 2;
    case B_DIV: return 3;
    default: return -1;
  }
}

// (L in c1) out c2  =  L res (fold(c1, c2)); families 0..3 = + - * /
// rules from models/mutate_device.py _combine_fold_table
inline bool combine_rule(int in_f, int out_f, double c1, double c2,
                         int* res_f, double* v) {
  if (in_f == 0 && out_f == 0) { *res_f = 0; *v = c1 + c2; return true; }
  if (in_f == 0 && out_f == 1) { *res_f = 0; *v = c1 - c2; return true; }
  if (in_f == 1 && out_f == 0) { *res_f = 1; *v = c1 - c2; return true; }
  if (in_f == 1 && out_f == 1) { *res_f = 1; *v = c1 + c2; return true; }
  if (in_f == 2 && out_f == 2) { *res_f = 2; *v = c1 * c2; return true; }
  if (in_f == 2 && out_f == 3) { *res_f = 2; *v = c1 / c2; return true; }
  if (in_f == 3 && out_f == 2) { *res_f = 3; *v = c1 / c2; return true; }
  if (in_f == 3 && out_f == 3) { *res_f = 3; *v = c1 * c2; return true; }
  return false;
}

// Simplify one tree in node form (all rewrites mutate nodes in place, so
// the root index never changes). Returns true if anything changed.
bool simplify_nodes(std::vector<Node>& nodes, int root,
                    const int32_t* una_map, int n_una,
                    const int32_t* bin_map, int n_bin,
                    bool do_fold, bool do_combine) {
  // operator-set index per family (+,-,*,/) for rewriting combine results
  int set_of_fam[4] = {-1, -1, -1, -1};
  for (int i = 0; i < n_bin; ++i) {
    int f = fam(bin_map[i]);
    if (f >= 0) set_of_fam[f] = i;
  }
  bool changed_any = false;
  for (int pass = 0; pass < 64; ++pass) {
    bool changed = false;
    // bottom-up walk via explicit stack (postorder on current graph)
    std::vector<int32_t> order;
    {
      std::vector<std::pair<int32_t, bool>> st{{root, false}};
      while (!st.empty()) {
        auto [idx, vis] = st.back();
        st.pop_back();
        if (vis) { order.push_back(idx); continue; }
        st.push_back({idx, true});
        if (nodes[idx].r >= 0) st.push_back({nodes[idx].r, false});
        if (nodes[idx].l >= 0) st.push_back({nodes[idx].l, false});
      }
    }
    for (int32_t idx : order) {
      Node& nd = nodes[idx];
      if (do_fold && nd.kind == KUNA && nodes[nd.l].kind == KCONST) {
        int32_t nat = nd.op < n_una ? una_map[nd.op] : -1;
        if (nat >= 0) {
          double v = apply_una(nat, nodes[nd.l].cval);
          if (std::isfinite(v)) {
            nd = {KCONST, 0, 0, v, -1, -1};
            changed = true;
            continue;
          }
        }
      }
      if (nd.kind != KBIN) continue;
      Node& lc = nodes[nd.l];
      Node& rc = nodes[nd.r];
      int32_t nat = nd.op < n_bin ? bin_map[nd.op] : -1;
      if (do_fold && nat >= 0 && lc.kind == KCONST && rc.kind == KCONST) {
        double v = apply_bin(nat, lc.cval, rc.cval);
        if (std::isfinite(v)) {
          nd = {KCONST, 0, 0, v, -1, -1};
          changed = true;
          continue;
        }
      }
      if (!do_combine || nat < 0) continue;
      int out_f = fam(nat);
      if (out_f < 0) continue;
      // combine: right child const, left child BIN with right child const
      if (rc.kind == KCONST && lc.kind == KBIN && lc.op < n_bin) {
        int in_f = fam(bin_map[lc.op]);
        if (in_f >= 0 && nodes[lc.r].kind == KCONST) {
          int res_f;
          double v;
          if (combine_rule(in_f, out_f, nodes[lc.r].cval, rc.cval,
                           &res_f, &v) &&
              std::isfinite(v) && set_of_fam[res_f] >= 0) {
            // nd := (lc.l  res_f  v)
            rc = {KCONST, 0, 0, v, -1, -1};
            nd.op = set_of_fam[res_f];
            nd.l = lc.l;
            changed = true;
            continue;
          }
        }
      }
      // commutative rotation: const left, non-const right (for + and *)
      if ((out_f == 0 || out_f == 2) && lc.kind == KCONST &&
          rc.kind != KCONST) {
        std::swap(nd.l, nd.r);
        changed = true;
      }
    }
    if (!changed) break;
    changed_any = true;
  }
  return changed_any;
}

}  // namespace

int64_t srt_simplify_batch(int64_t T, int32_t L,
                           int32_t* kind, int32_t* op, int32_t* feat,
                           float* cval, int32_t* length,
                           const int32_t* una_map, int32_t n_una,
                           const int32_t* bin_map, int32_t n_bin,
                           int32_t do_fold, int32_t do_combine) {
  int64_t n_changed = 0;
  std::vector<Node> nodes;
  for (int64_t t = 0; t < T; ++t) {
    int32_t* k = kind + t * L;
    int32_t* o = op + t * L;
    int32_t* f = feat + t * L;
    float* c = cval + t * L;
    int32_t n = length[t];
    if (n <= 0 || n > L) continue;
    int root = build_nodes(k, o, f, c, n, nodes);
    if (root < 0) continue;
    if (!simplify_nodes(nodes, root, una_map, n_una, bin_map, n_bin,
                        do_fold != 0, do_combine != 0))
      continue;
    int32_t n2 = emit_postfix(nodes, root, L, k, o, f, c);
    if (n2 > 0) {
      length[t] = n2;
      ++n_changed;
    }
  }
  return n_changed;
}

// ---------------------------------------------------------------------------
// Multithreaded batched evaluator — the reference's CPU path
// (DynamicExpressions eval_tree_array over a multithreaded population).
// X row-major (nfeat, n) f32; y out (T, n) f32; ok out (T,) u8.
// ---------------------------------------------------------------------------

// y_target/loss_out are optional (may be NULL): when given, each tree also
// gets its mean-squared-error against y_target (the reference's
// score_func = eval + loss reduction, src/LossFunctions.jl:86-92) — used
// for honest CPU-anchor benchmarking of the full scoring path.
int32_t srt_eval_batch(int64_t T, int32_t L,
                       const int32_t* kind, const int32_t* op,
                       const int32_t* feat, const float* cval,
                       const int32_t* length,
                       const float* X, int32_t nfeat, int64_t n,
                       const int32_t* una_map, int32_t n_una,
                       const int32_t* bin_map, int32_t n_bin,
                       float* y, uint8_t* ok, int32_t n_threads,
                       const float* y_target, float* loss_out) {
  if (n_threads <= 0) {
    n_threads = static_cast<int32_t>(std::thread::hardware_concurrency());
    if (n_threads <= 0) n_threads = 1;
  }
  n_threads = static_cast<int32_t>(
      std::min<int64_t>(n_threads, std::max<int64_t>(T, 1)));
  std::vector<uint8_t> valid_ops(static_cast<size_t>(T), 1);

  auto worker = [&](int64_t t0, int64_t t1) {
    constexpr int64_t RB = 512;  // row block: keeps the stack in L1
    std::vector<double> stack(static_cast<size_t>(L / 2 + 2) * RB);
    for (int64_t t = t0; t < t1; ++t) {
      const int32_t* k = kind + t * L;
      const int32_t* o = op + t * L;
      const int32_t* f = feat + t * L;
      const float* c = cval + t * L;
      int32_t len = length[t];
      float* yt = y + t * n;
      bool good = len > 0 && len <= L;
      if (good) {  // validate structure + op indices once per tree
        int32_t sp = 0;
        for (int32_t i = 0; i < len && good; ++i) {
          switch (k[i]) {
            case KCONST: case KVAR: ++sp; break;
            case KUNA:
              good = sp >= 1 && o[i] < n_una && una_map[o[i]] >= 0;
              break;
            case KBIN:
              good = sp >= 2 && o[i] < n_bin && bin_map[o[i]] >= 0;
              --sp;
              break;
            default: good = false;
          }
          good = good && (k[i] != KVAR || (f[i] >= 0 && f[i] < nfeat));
        }
        good = good && sp == 1;
      }
      if (!good) {
        for (int64_t r = 0; r < n; ++r) yt[r] = std::nanf("");
        ok[t] = 0;
        if (loss_out) loss_out[t] = std::nanf("");
        continue;
      }
      bool finite = true;
      double loss_acc = 0.0;
      for (int64_t r0 = 0; r0 < n; r0 += RB) {
        int64_t rb = std::min(RB, n - r0);
        int32_t sp = 0;
        for (int32_t i = 0; i < len; ++i) {
          double* out_row = &stack[static_cast<size_t>(sp) * RB];
          switch (k[i]) {
            case KCONST: {
              double v = c[i];
              for (int64_t r = 0; r < rb; ++r) out_row[r] = v;
              ++sp;
              break;
            }
            case KVAR: {
              const float* xr = X + static_cast<int64_t>(f[i]) * n + r0;
              for (int64_t r = 0; r < rb; ++r) out_row[r] = xr[r];
              ++sp;
              break;
            }
            case KUNA: {
              double* a = &stack[static_cast<size_t>(sp - 1) * RB];
              int32_t nat = una_map[o[i]];
              for (int64_t r = 0; r < rb; ++r) a[r] = apply_una(nat, a[r]);
              break;
            }
            case KBIN: {
              double* a = &stack[static_cast<size_t>(sp - 2) * RB];
              double* b = &stack[static_cast<size_t>(sp - 1) * RB];
              int32_t nat = bin_map[o[i]];
              for (int64_t r = 0; r < rb; ++r)
                a[r] = apply_bin(nat, a[r], b[r]);
              --sp;
              break;
            }
          }
        }
        const double* res = &stack[0];
        for (int64_t r = 0; r < rb; ++r) {
          float v = static_cast<float>(res[r]);
          yt[r0 + r] = v;
          finite = finite && std::isfinite(v);
        }
        if (y_target) {
          for (int64_t r = 0; r < rb; ++r) {
            double d = res[r] - y_target[r0 + r];
            loss_acc += d * d;
          }
        }
      }
      ok[t] = finite ? 1 : 0;
      if (loss_out) {
        loss_out[t] = finite ? static_cast<float>(loss_acc / n)
                             : std::nanf("");
      }
    }
  };

  if (n_threads == 1) {
    worker(0, T);
  } else {
    std::vector<std::thread> threads;
    int64_t chunk = (T + n_threads - 1) / n_threads;
    for (int32_t i = 0; i < n_threads; ++i) {
      int64_t t0 = i * chunk, t1 = std::min<int64_t>(T, t0 + chunk);
      if (t0 >= t1) break;
      threads.emplace_back(worker, t0, t1);
    }
    for (auto& th : threads) th.join();
  }
  return 0;
}

// ---------------------------------------------------------------------------
// CSV loader (host IO). Two-phase: probe shape, then fill a caller buffer.
// Accepts an optional header row (detected: any field that fails to parse as
// a float). Delimiter auto-detect among [',', '\t', ';', ' '] when delim=0.
// ---------------------------------------------------------------------------

namespace {

char detect_delim(const std::string& line) {
  // space is a last resort: header names may themselves contain spaces
  const char cands[] = {',', '\t', ';'};
  char best = ',';
  size_t best_n = 0;
  for (char d : cands) {
    size_t cnt = 0;
    for (char ch : line) cnt += ch == d;
    if (cnt > best_n) { best_n = cnt; best = d; }
  }
  if (best_n == 0) return ' ';
  return best;
}

std::vector<std::string> split_fields(const std::string& line, char d) {
  std::vector<std::string> out;
  size_t i = 0;
  while (i <= line.size()) {
    size_t j = line.find(d, i);
    if (j == std::string::npos) j = line.size();
    std::string fld = line.substr(i, j - i);
    // trim
    size_t a = fld.find_first_not_of(" \t\r");
    size_t b = fld.find_last_not_of(" \t\r");
    out.push_back(a == std::string::npos ? "" : fld.substr(a, b - a + 1));
    i = j + 1;
    if (j == line.size()) break;
  }
  // drop trailing empties caused by space-delimited runs
  while (out.size() > 1 && out.back().empty()) out.pop_back();
  return out;
}

bool parse_field(const std::string& s, double* v) {
  if (s.empty()) return false;
  char* end = nullptr;
  *v = std::strtod(s.c_str(), &end);
  return end && *end == '\0';
}

}  // namespace

int32_t srt_csv_probe(const char* path, char delim, int64_t* rows,
                      int64_t* cols, int32_t* has_header,
                      char* header_out, int64_t header_cap) {
  FILE* fp = std::fopen(path, "r");
  if (!fp) return -1;
  std::string line;
  char buf[1 << 16];
  int64_t r = 0, c = 0;
  int hdr = -1;
  char d = delim;
  while (std::fgets(buf, sizeof buf, fp)) {
    line.assign(buf);
    while (!line.empty() && (line.back() == '\n' || line.back() == '\r'))
      line.pop_back();
    if (line.empty()) continue;
    if (!d) d = detect_delim(line);
    auto fields = split_fields(line, d);
    if (hdr < 0) {
      double v;
      hdr = 0;
      for (const auto& f : fields)
        if (!parse_field(f, &v)) { hdr = 1; break; }
      c = static_cast<int64_t>(fields.size());
      if (hdr == 1 && header_out && header_cap > 0) {
        std::string joined;
        for (size_t i = 0; i < fields.size(); ++i) {
          if (i) joined += '\n';
          joined += fields[i];
        }
        std::snprintf(header_out, static_cast<size_t>(header_cap), "%s",
                      joined.c_str());
      }
      if (hdr == 1) continue;  // header row doesn't count
    }
    ++r;
  }
  std::fclose(fp);
  *rows = r;
  *cols = c;
  *has_header = hdr == 1;
  return 0;
}

int32_t srt_csv_read(const char* path, char delim, int32_t skip_header,
                     double* out, int64_t rows, int64_t cols) {
  FILE* fp = std::fopen(path, "r");
  if (!fp) return -1;
  std::string line;
  char buf[1 << 16];
  char d = delim;
  int64_t r = 0;
  bool first = true;
  int rc = 0;
  while (std::fgets(buf, sizeof buf, fp) && r < rows) {
    line.assign(buf);
    while (!line.empty() && (line.back() == '\n' || line.back() == '\r'))
      line.pop_back();
    if (line.empty()) continue;
    if (!d) d = detect_delim(line);
    if (first && skip_header) { first = false; continue; }
    first = false;
    auto fields = split_fields(line, d);
    if (static_cast<int64_t>(fields.size()) != cols) { rc = -2; break; }
    for (int64_t c = 0; c < cols; ++c) {
      double v;
      if (!parse_field(fields[static_cast<size_t>(c)], &v)) { rc = -3; break; }
      out[r * cols + c] = v;
    }
    if (rc) break;
    ++r;
  }
  std::fclose(fp);
  if (rc) return rc;
  return r == rows ? 0 : -4;
}

}  // extern "C"
